"""Resident multi-tenant solve server with cross-request coalescing.

:class:`SolveServer` is the long-lived front of ROADMAP item 1: it owns
one sweep configuration (base design, axes, sea states, iteration count,
optional aero-servo wind cases) and keeps the chunk executables, the
template memo, and the resident variant batch warm on-device forever.
Callers submit small design-point batches (1-50 points each); the
server packs pending requests into *rounds* — one ``sweep(grid=...)``
call over the concatenated points — so every request shares the same
fixed-shape padded chunks the mesh executor already runs.  Coalescing
is the whole throughput story: N cohabiting requests cost the chunks of
ONE sweep, not N.

Robustness contract (docs/serving.md spells out the full matrix):

* **Admission / backpressure** — the pending-design queue is bounded;
  a full queue rejects with :class:`ServerSaturated` (HTTP 429 on the
  wire), an oversized request with :class:`RequestRejected`
  (``too_large``).  Rejection is *typed and immediate* — the server
  never silently queues unbounded work.
* **Priorities + tenant fairness** — lower ``priority`` schedules
  first; within a priority class, round composition round-robins across
  tenants so one chatty tenant cannot starve the rest.
* **Deadlines** — a request past its deadline is failed (typed
  :class:`DeadlineExceeded`) at round composition — its rows are never
  dispatched — or at delivery when the round outlived it.  A round
  whose members carry deadlines runs under
  :func:`~raft_tpu.parallel.executor.call_with_deadline` (the
  watchdog's enforcement primitive) sized to the latest member deadline
  plus a grace, so a wedged round cannot outlive every caller's
  interest.
* **Cancellation** — cancelling a queued request masks its rows out of
  all future rounds; cancelling mid-round discards its slice at
  delivery.  Cohabiting requests are never stalled either way.
* **Quarantine isolation** — a poison design inside a shared chunk is
  bisected out by ``run_isolated`` *inside* the sweep; cohabiting rows
  still compute.  The per-request result carries its own ``status``
  rows, so one tenant's NaN storm degrades only that tenant's answers.
* **Circuit breaker** — repeated quarantines of the same design
  fingerprint trip :class:`~raft_tpu.robust.quarantine.CircuitBreaker`;
  further submissions of that fingerprint fast-fail at admission for
  the cooldown instead of burning bisection rounds.
* **Graceful degradation** — ``close(drain=True)`` (and SIGTERM via the
  chaos ``preempt`` routing, :func:`raft_tpu.robust.chaos.
  register_preempt_hook`) drains: the in-flight round completes and
  delivers, queued requests checkpoint to a resumable JSON
  (``drain_path``) and fail typed.  Device loss mid-round re-meshes
  inside ``sweep()`` and the round completes on the survivors — no
  request fails.

Bit-identity: rounds run the same executables at the same chunk extent
as a direct ``sweep(grid=points, chunk_size=cfg['chunk_size'])`` call,
and the chunk programs are vmapped row-independent — so each request's
slice of a coalesced round is bit-identical to solving it alone
(pinned by tests/test_serve.py and scripts/serve_check.py).
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time

import numpy as np

from ..config import serve_config
from ..obs import ledger as obs_ledger
from ..obs import log as obs_log
from ..parallel.executor import LatencyWindow, call_with_deadline
from ..robust import STATUS_QUARANTINED
from ..robust import chaos as chaos_mod
from ..robust.quarantine import CircuitBreaker

__all__ = [
    "SolveServer",
    "Ticket",
    "RequestRejected",
    "ServerSaturated",
    "RequestCancelled",
    "DeadlineExceeded",
    "RequestFailed",
]

_LOG = obs_log.get_logger("serve.server")

# per-request result keys sliced out of a round's sweep output
_RESULT_KEYS = ("motion_std", "AxRNA_std", "mass", "displacement", "GMT",
                "status")


class RequestRejected(RuntimeError):
    """Typed admission rejection; ``reason`` is the ledger reason code
    (``saturated`` | ``too_large`` | ``deadline`` | ``breaker`` |
    ``closed``)."""

    http_status = 400

    def __init__(self, reason, detail=""):
        super().__init__(f"request rejected ({reason})"
                         + (f": {detail}" if detail else ""))
        self.reason = reason


class ServerSaturated(RequestRejected):
    """The bounded queue is full — shed load, retry later (HTTP 429)."""

    http_status = 429

    def __init__(self, detail=""):
        super().__init__("saturated", detail)


class RequestCancelled(RuntimeError):
    """The request was cancelled before delivery."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before its results were ready."""


class RequestFailed(RuntimeError):
    """The request's round failed after exhausting its retry budget."""


def point_fingerprint(point) -> str:
    """Stable fingerprint of one design point (the circuit-breaker key
    and the chaos-plan key for request-layer seams)."""
    h = hashlib.sha256()
    for v in point:
        arr = np.asarray(v)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


class _Request:
    """Internal request record; callers hold the :class:`Ticket` view."""

    __slots__ = ("id", "tenant", "points", "fps", "priority", "deadline",
                 "deadline_s", "t_accept", "seq", "retries_left",
                 "cancelled", "event", "result", "error", "synthetic")

    def __init__(self, rid, tenant, points, fps, priority, deadline,
                 deadline_s, seq, retries_left, synthetic=False):
        self.id = rid
        self.tenant = tenant
        self.points = points
        self.fps = fps
        self.priority = priority
        self.deadline = deadline        # absolute monotonic, or None
        self.deadline_s = deadline_s    # as submitted (ledger)
        self.t_accept = time.monotonic()
        self.seq = seq
        self.retries_left = retries_left
        self.cancelled = False
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.synthetic = synthetic      # chaos req_flood filler

    def expired(self, now=None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                >= self.deadline)


class Ticket:
    """Caller-facing handle for one submitted request."""

    def __init__(self, server, req):
        self._server = server
        self._req = req

    @property
    def id(self) -> str:
        return self._req.id

    @property
    def done(self) -> bool:
        return self._req.event.is_set()

    def result(self, timeout=None) -> dict:
        """Block for this request's results.

        Returns the per-request result dict (``grid``, ``motion_std``,
        ``AxRNA_std``, ``mass``, ``displacement``, ``GMT``, ``status``,
        ``health``) or raises the request's typed failure
        (:class:`RequestCancelled`, :class:`DeadlineExceeded`,
        :class:`RequestFailed`).  ``timeout=None`` waits forever.
        """
        if not self._req.event.wait(timeout):
            raise TimeoutError(
                f"request {self._req.id} still pending after {timeout}s")
        if self._req.error is not None:
            raise self._req.error
        return self._req.result

    def cancel(self) -> bool:
        """Cancel the request; True when the cancel landed before
        delivery (False when results were already delivered)."""
        return self._server._cancel(self._req)


class SolveServer:
    """Long-lived coalescing solve server over one sweep configuration.

    Parameters mirror :func:`raft_tpu.sweep.sweep` minus the axes'
    *values* — requests supply the design points; ``axes`` fixes the
    axis *paths* (one value per path per point).  ``config`` overrides
    :func:`raft_tpu.config.serve_config` keys; ``chaos`` arms the
    request-layer chaos seams (``req_flood`` / ``slow_client`` /
    ``cancel_storm``) on the server's own plan — sweep-level seams go
    through :meth:`inject_chaos`, which arms the NEXT round's sweep.
    """

    def __init__(self, base_design, axes, sea_states, *, n_iter=15,
                 wind=None, devices=None, device=None, health=None,
                 config=None, chaos=None):
        self.cfg = serve_config(config)
        self._base_design = base_design
        self._axes = [(p, list(v)) for p, v in axes]
        self._sea_states = list(sea_states)
        self._n_iter = int(n_iter)
        self._wind = wind
        self._devices = devices
        self._device = device
        self._health = health

        self._lock = threading.Condition()
        self._pending: list = []       # admitted, not yet in a round
        self._pending_designs = 0
        self._round_no = 0
        self._req_seq = itertools.count()
        self._tenant_rr: list = []     # round-robin order memory
        self._closing = False
        self._closed = threading.Event()
        self._worker = None
        self._next_chaos = None        # one-shot sweep-level spec
        self._latency = LatencyWindow()
        self._t_started = None
        self._counts = {"accepted": 0, "rejected": 0, "completed": 0,
                        "failed": 0, "cancelled": 0, "deadline": 0,
                        "rounds": 0, "coalesced_designs": 0, "drains": 0}

        self._run = obs_ledger.NULL_RUN
        if obs_ledger.observing():
            from ..sweep import _design_hash

            self._run = obs_ledger.start_run(
                "serve",
                fingerprint={"design": _design_hash(base_design)[:16],
                             "axes": [str(p) for p, _ in self._axes],
                             "n_cases": len(self._sea_states)},
                meta={"n_iter": self._n_iter,
                      "chunk_size": int(self.cfg["chunk_size"]),
                      "wind": wind is not None})
        self._plan = chaos_mod.plan_for(
            "serve", run=self._run, chaos=chaos)
        self._breaker = CircuitBreaker(
            threshold=self.cfg["breaker_threshold"],
            cooldown_s=self.cfg["breaker_cooldown_s"], run=self._run)

    # -- lifecycle --------------------------------------------------------

    def _bucket(self, n) -> int:
        """Round a round's design count up to its size bucket.

        Rounds are padded (row repetition — rows are vmap-independent,
        so padding never changes a real row's bits) to a power-of-two
        multiple of ``chunk_size``.  Two invariants follow: the chunk
        extent is ALWAYS ``chunk_size`` (a 1-design round runs the same
        executables as a full one), and the resident variant-batch
        shape takes at most ``log2(max_round/chunk) + 1`` distinct
        values — so the executable set is small, warmable, and a warmed
        server dispatches rounds of any composition with zero real XLA
        compiles.
        """
        b = int(self.cfg["chunk_size"])
        while b < n:
            b *= 2
        return b

    def _warm_pad(self, grid) -> list:
        return grid + [grid[0]] * (self._bucket(len(grid)) - len(grid))

    def start(self, warm=True):
        """Warm the executables and start the round worker.

        ``warm=True`` runs :func:`~raft_tpu.sweep.precompile` over one
        chunk-sized grid (compile the chunk executables, dispatch
        nothing); ``warm="buckets"`` additionally solves one throwaway
        micro-round per size bucket, so the dispatch-time programs
        (resident chunk selector) are hot for every round shape and the
        server serves with zero real XLA compiles from the first
        request."""
        from ..sweep import precompile, sweep

        if self._worker is not None:
            raise RuntimeError("server already started")
        if warm:
            pt = tuple(v[0] for _, v in self._axes)
            warm_grid = [pt] * int(self.cfg["chunk_size"])
            precompile(self._base_design, self._axes, self._sea_states,
                       n_iter=self._n_iter, wind=self._wind,
                       devices=self._devices, device=self._device,
                       health=self._health,
                       chunk_size=self.cfg["chunk_size"], grid=warm_grid)
            if warm == "buckets":
                top = self._bucket(int(self.cfg["max_round_designs"]))
                b = int(self.cfg["chunk_size"])
                while True:
                    sweep(self._base_design, self._axes, self._sea_states,
                          n_iter=self._n_iter, wind=self._wind,
                          devices=self._devices, device=self._device,
                          health=self._health,
                          chunk_size=self.cfg["chunk_size"],
                          grid=[pt] * b)
                    if b >= top:
                        break
                    b *= 2
        self._t_started = time.monotonic()
        self._worker = threading.Thread(
            target=self._serve_loop, name="raft-tpu-serve", daemon=True)
        self._worker.start()
        chaos_mod.register_preempt_hook(self._preempt_drain)
        return self

    def close(self, drain=True, timeout=60.0):
        """Stop the server.

        ``drain=True`` finishes and delivers the in-flight round, then
        checkpoints still-queued requests to ``cfg['drain_path']`` (when
        set) and fails them typed (``RequestRejected('closed')``).
        ``drain=False`` abandons the queue the same way without waiting
        for the current round.
        """
        with self._lock:
            if self._closing:
                self._closed.wait(timeout)
                return
            self._closing = True
            self._lock.notify_all()
        if self._worker is not None:
            self._worker.join(timeout if drain else 1.0)
        self._drain_queue(checkpoint=True)
        chaos_mod.unregister_preempt_hook(self._preempt_drain)
        self._closed.set()
        self._run.finish(ok=True, counts=dict(self._counts))
        self._run.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- submission API ---------------------------------------------------

    def submit(self, points, *, tenant="default", priority=None,
               deadline_s=None, _synthetic=False) -> Ticket:
        """Admit one request (a list of design-point tuples).

        Raises the typed admission errors documented on the class;
        returns a :class:`Ticket` whose ``result()`` blocks for the
        coalesced solve.
        """
        points = [tuple(pt) for pt in points]
        n_ax = len(self._axes)
        for pt in points:
            if len(pt) != n_ax:
                raise RequestRejected(
                    "too_large", f"point has {len(pt)} values for "
                                 f"{n_ax} axes")
        priority = (self.cfg["default_priority"] if priority is None
                    else int(priority))
        if deadline_s is None:
            deadline_s = self.cfg["default_deadline_s"]
        deadline_s = float(deadline_s)
        rid = f"req-{next(self._req_seq):06d}"
        fps = [point_fingerprint(pt) for pt in points]

        reason = detail = None
        if not points or len(points) > self.cfg["max_request_designs"]:
            reason, detail = "too_large", (
                f"{len(points)} designs (limit "
                f"{self.cfg['max_request_designs']})")
        elif deadline_s < 0:
            reason, detail = "deadline", "deadline already expired"
        else:
            tripped = [fp for fp in fps if not self._breaker.allows(fp)]
            if tripped:
                reason, detail = "breaker", (
                    f"{len(tripped)} design(s) circuit-broken "
                    f"(first: {tripped[0]})")
        if reason is None:
            with self._lock:
                if self._closing:
                    reason = "closed"
                elif (self._pending_designs + len(points)
                      > self.cfg["max_pending_designs"]):
                    reason, detail = "saturated", (
                        f"{self._pending_designs} designs queued (bound "
                        f"{self.cfg['max_pending_designs']})")
                else:
                    req = _Request(
                        rid, str(tenant), points, fps, priority,
                        (time.monotonic() + deadline_s
                         if deadline_s > 0 else None),
                        deadline_s, next(self._req_seq),
                        self.cfg["retry_rounds"], synthetic=_synthetic)
                    self._pending.append(req)
                    self._pending_designs += len(points)
                    self._counts["accepted"] += 1
                    self._lock.notify_all()
        if reason is not None:
            self._counts["rejected"] += 1
            self._run.emit("request_reject", request=rid, reason=reason,
                           tenant=str(tenant), designs=len(points))
            if reason == "saturated":
                raise ServerSaturated(detail)
            raise RequestRejected(reason, detail or "")
        self._run.emit("request_accept", request=rid, tenant=str(tenant),
                       designs=len(points), priority=priority,
                       deadline_s=deadline_s or None)
        return Ticket(self, req)

    def solve(self, points, timeout=None, **kw) -> dict:
        """``submit`` + ``result`` in one call (the blocking API)."""
        return self.submit(points, **kw).result(timeout)

    def inject_chaos(self, spec) -> None:
        """Arm ``spec`` (a sweep-level chaos spec string) for the NEXT
        round only — the deterministic way to drive ``device_lost`` /
        ``preempt`` through a serving process."""
        with self._lock:
            self._next_chaos = spec

    # -- introspection ----------------------------------------------------

    def stats(self) -> dict:
        """Live counters + latency percentiles (the serve_check /
        history-store payload)."""
        with self._lock:
            counts = dict(self._counts)
            queued = len([r for r in self._pending if not r.cancelled])
        elapsed = (time.monotonic() - self._t_started
                   if self._t_started else 0.0)
        p50 = self._latency.percentile(50)
        p99 = self._latency.percentile(99)
        return {
            **counts,
            "queued": queued,
            "elapsed_s": round(elapsed, 3),
            "requests_per_s": (round(counts["completed"] / elapsed, 3)
                               if elapsed > 0 else None),
            "p50_s": None if p50 is None else round(p50, 6),
            "p99_s": None if p99 is None else round(p99, 6),
            "breaker_open": self._breaker.tripped(),
        }

    # -- internal: cancellation / failure delivery ------------------------

    def _cancel(self, req) -> bool:
        with self._lock:
            if req.event.is_set() or req.cancelled:
                return False
            req.cancelled = True
            self._lock.notify_all()
        # delivery happens at the next round composition (queued) or at
        # the in-flight round's delivery (dispatched); either way the
        # caller unblocks with the typed error now
        self._deliver_error(req, RequestCancelled(
            f"request {req.id} cancelled"), "request_cancel")
        return True

    def _deliver_error(self, req, err, event):
        already = req.event.is_set()
        if already:
            return
        req.error = err
        req.event.set()
        counter = {"request_cancel": "cancelled",
                   "request_deadline": "deadline"}.get(event)
        with self._lock:
            if counter:
                self._counts[counter] += 1
            elif event == "request_done":
                self._counts["failed"] += 1
        if event == "request_done":
            self._run.emit("request_done", request=req.id, ok=False,
                           tenant=req.tenant,
                           error=f"{type(err).__name__}: {err}")
        else:
            kw = {"deadline_s": req.deadline_s} \
                if event == "request_deadline" else {}
            self._run.emit(event, request=req.id, tenant=req.tenant, **kw)

    def _deliver_result(self, req, result):
        if req.event.is_set():
            return
        seconds = time.monotonic() - req.t_accept
        req.result = result
        delay = None
        if self._plan is not None:
            rule = self._plan.fires("slow_client", key=req.seq)
            if rule is not None:
                delay = rule.secs
        if delay:
            # a slow reader stalls only its own delivery: the unblock
            # runs on a timer thread, never the round worker
            threading.Timer(delay, req.event.set).start()
        else:
            req.event.set()
        with self._lock:
            self._counts["completed"] += 1
        self._latency.observe(seconds)
        self._run.emit("request_done", request=req.id, ok=True,
                       tenant=req.tenant, seconds=round(seconds, 6))

    # -- internal: drain --------------------------------------------------

    def _preempt_drain(self) -> bool:
        """Chaos ``preempt`` routing for a resident server: checkpoint
        the queue, emit the drill, KEEP serving (return True = handled,
        no SIGTERM is delivered)."""
        path = self._checkpoint_pending()
        with self._lock:
            self._counts["drains"] += 1
            queued = len(self._pending)
        self._run.emit("preempt", signal="drill", drained=queued,
                       checkpoint=path, resident=True)
        obs_log.warn(
            _LOG, "serve: preempt drill — queue checkpointed "
                  f"({queued} request(s)); still serving", RuntimeWarning)
        return True

    def _checkpoint_pending(self):
        """Write still-queued request specs to the resumable drain
        JSON; returns the path (None when unconfigured)."""
        path = self.cfg["drain_path"]
        if not path:
            return None
        with self._lock:
            specs = [{"tenant": r.tenant,
                      "points": [list(pt) for pt in r.points],
                      "priority": r.priority,
                      "deadline_s": r.deadline_s or None}
                     for r in self._pending
                     if not r.cancelled and not r.synthetic]
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"requests": specs}, fh)
        os.replace(tmp, path)
        return path

    def resume_pending(self, path=None) -> int:
        """Resubmit requests from a drain checkpoint; returns how many
        were re-admitted (admission control applies as usual)."""
        path = path or self.cfg["drain_path"]
        if not path or not os.path.exists(path):
            return 0
        with open(path, encoding="utf-8") as fh:
            specs = json.load(fh).get("requests", [])
        n = 0
        for spec in specs:
            try:
                self.submit(spec["points"], tenant=spec.get("tenant",
                                                            "default"),
                            priority=spec.get("priority"),
                            deadline_s=spec.get("deadline_s"))
                n += 1
            except RequestRejected:
                continue
        return n

    def _drain_queue(self, checkpoint):
        if checkpoint:
            self._checkpoint_pending()
        with self._lock:
            leftover, self._pending = self._pending, []
            self._pending_designs = 0
        for req in leftover:
            if not req.cancelled:
                self._deliver_error(
                    req, RequestRejected("closed", "server closed"),
                    "request_done")

    # -- internal: the round worker ---------------------------------------

    def _serve_loop(self):
        while True:
            with self._lock:
                while not self._closing and not any(
                        not r.cancelled for r in self._pending):
                    self._lock.wait(timeout=0.5)
                if self._closing:
                    return
            batch = self._compose_round()
            if batch:
                self._run_round(batch)

    def _fire_request_chaos(self):
        """req_flood / cancel_storm at round composition."""
        if self._plan is None:
            return
        rule = self._plan.fires("req_flood", key=self._round_no)
        if rule is not None:
            flood_pt = tuple(v[0] for _, v in self._axes)
            shed = 0
            tickets = []
            for _ in range(rule.count):
                try:
                    tickets.append(self.submit(
                        [flood_pt], tenant="_chaos", _synthetic=True))
                except RequestRejected:
                    shed += 1
            # the flood's job is driving admission control, not burning
            # device time: cancel what got in
            for t in tickets:
                t.cancel()
            _LOG.info("chaos req_flood: %d injected, %d shed",
                      len(tickets), shed)
        rule = self._plan.fires("cancel_storm", key=self._round_no)
        if rule is not None:
            with self._lock:
                victims = [r for r in self._pending
                           if not r.cancelled][:rule.count]
            for r in victims:
                self._cancel(r)

    def _compose_round(self) -> list:
        """Pick the next round's members: drop cancelled, expire
        overdue, order by (priority, fair tenant round-robin), pack to
        the round budget."""
        self._fire_request_chaos()
        now = time.monotonic()
        expired, members = [], []
        with self._lock:
            keep = []
            for r in self._pending:
                if r.cancelled or r.event.is_set():
                    self._pending_designs -= len(r.points)
                elif r.expired(now):
                    expired.append(r)
                    self._pending_designs -= len(r.points)
                else:
                    keep.append(r)
            # priority first, then fair round-robin over tenants inside
            # each class: take one request per tenant per cycle, tenants
            # cycled in order of their oldest queued request
            keep.sort(key=lambda r: (r.priority, r.seq))
            budget = self.cfg["max_round_designs"]
            by_tenant: dict = {}
            for r in keep:
                by_tenant.setdefault((r.priority, r.tenant), []).append(r)
            classes: dict = {}
            for (prio, tenant), rs in by_tenant.items():
                classes.setdefault(prio, []).append((rs[0].seq, tenant, rs))
            used = 0
            for prio in sorted(classes):
                lanes = [list(rs) for _, _, rs in sorted(classes[prio])]
                while lanes:
                    progressed = False
                    for lane in list(lanes):
                        if not lane:
                            lanes.remove(lane)
                            continue
                        r = lane[0]
                        if used + len(r.points) > budget:
                            lanes.remove(lane)
                            continue
                        lane.pop(0)
                        members.append(r)
                        used += len(r.points)
                        progressed = True
                    if not progressed:
                        break
            for r in members:
                keep.remove(r)
                self._pending_designs -= len(r.points)
            self._pending = keep
        for r in expired:
            self._deliver_error(r, DeadlineExceeded(
                f"request {r.id} missed its {r.deadline_s:.3f}s deadline "
                "before dispatch"), "request_deadline")
        return members

    def _requeue(self, reqs):
        with self._lock:
            for r in reqs:
                self._pending.insert(0, r)
                self._pending_designs += len(r.points)
            self._lock.notify_all()

    def _run_round(self, members):
        from ..sweep import sweep

        self._round_no += 1
        round_no = self._round_no
        real = [pt for r in members for pt in r.points]
        grid = self._warm_pad(real)
        with self._lock:
            chaos_spec, self._next_chaos = self._next_chaos, None
            self._counts["rounds"] += 1
            self._counts["coalesced_designs"] += len(real)
        self._run.emit("serve_round", round=round_no,
                       requests=len(members), designs=len(real),
                       padded=len(grid))

        def _solve():
            return sweep(self._base_design, self._axes, self._sea_states,
                         n_iter=self._n_iter, wind=self._wind,
                         devices=self._devices, device=self._device,
                         health=self._health,
                         chunk_size=self.cfg["chunk_size"],
                         chaos=chaos_spec if chaos_spec else False,
                         grid=grid)

        deadlines = [r.deadline for r in members if r.deadline is not None]
        try:
            if deadlines:
                budget = (max(deadlines) - time.monotonic()
                          + self.cfg["deadline_grace_s"])
                out = call_with_deadline(
                    _solve, max(budget, 0.001),
                    what=f"serve round {round_no}")
            else:
                out = _solve()
        except BaseException as err:  # noqa: BLE001 - typed fan-out below
            self._fail_round(members, err)
            return
        self._deliver_round(members, out)

    def _fail_round(self, members, err):
        now = time.monotonic()
        retry = []
        for r in members:
            if r.cancelled or r.event.is_set():
                continue
            if r.expired(now):
                self._deliver_error(r, DeadlineExceeded(
                    f"request {r.id} missed its deadline "
                    f"({type(err).__name__} in round)"),
                    "request_deadline")
                continue
            if r.retries_left > 0:
                r.retries_left -= 1
                retry.append(r)
            else:
                self._deliver_error(r, RequestFailed(
                    f"request {r.id} failed after retries: "
                    f"{type(err).__name__}: {err}"), "request_done")
        if retry:
            _LOG.warning(
                "serve: round failed (%s: %s); requeueing %d request(s)",
                type(err).__name__, err, len(retry))
            self._requeue(retry)

    def _deliver_round(self, members, out):
        offset = 0
        for r in members:
            n = len(r.points)
            sl = slice(offset, offset + n)
            offset += n
            if r.cancelled or r.event.is_set():
                continue
            if r.expired():
                self._deliver_error(r, DeadlineExceeded(
                    f"request {r.id} completed past its "
                    f"{r.deadline_s:.3f}s deadline"), "request_deadline")
                continue
            status_rows = np.asarray(out["status"][sl])
            for fp, st in zip(r.fps, status_rows):
                if int(st) == STATUS_QUARANTINED:
                    self._breaker.record_failure(fp)
                else:
                    self._breaker.record_success(fp)
            result = {"grid": list(out["grid"][sl])}
            for key in _RESULT_KEYS:
                result[key] = np.asarray(out[key])[sl].copy()
            result["health"] = {
                k: np.asarray(v)[sl].copy()
                for k, v in out["health"].items()}
            self._deliver_result(r, result)
