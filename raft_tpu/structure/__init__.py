"""Structural components: members (strip theory), rotors, towers."""

from . import member  # noqa: F401
