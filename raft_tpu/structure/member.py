"""Strip-theory member: design compilation and batched physics kernels.

Re-design of the reference Member class (/root/reference/raft/raft_member.py)
for a TPU execution model.  The reference mutates per-node NumPy arrays in
Python loops; here a member is split into

- a **static topology** (station/segment counts, node layout, cap branch
  choices, cross-section shape) fixed at design-compile time, and
- a **geometry pytree** of jnp arrays (station positions, diameters,
  thicknesses, ballast, drag/added-mass coefficient tables)

so that every physics quantity — inertia matrix, hydrostatics, Morison
added mass / excitation coefficients — is a pure jnp function of
(topology, geometry, pose).  That makes the whole member layer
differentiable and ``vmap``-able over design parameters (the sweep axis)
and lets XLA fuse the node loops the reference runs in Python.

Reference behavior parity targets: Member.__init__ station/strip setup
(raft_member.py:67-220), setPosition (:245-304), getInertia (:307-707),
getHydrostatics (:712-874), calcHydroConstants/calcImat/getCmSides
(:877-1088).

Reference-method -> function mapping (the class methods become pure
functions over the compiled (topology, geometry, pose) triple):

=======================  =====================================
reference Member method  this module
=======================  =====================================
__init__                 compile_member
setPosition              member_pose
getInertia               member_inertia
getHydrostatics          member_hydrostatics
calcHydroConstants       member_hydro_constants
calcImat                 member_hydro_constants (Imat output)
getCmSides (MacCamy-F.)  _imat_mcf
correction_KAY           hydro.second_order._kim_and_yue
plot                     Model.plot / FOWT.plot draw the poses
=======================  =====================================
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import GRAVITY, RHO_WATER
from ..ops import frustum, transforms
from ..schema import get_from_dict

# ---------------------------------------------------------------------------
# compiled member containers
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MemberTopology:
    """Static (hashable) member structure resolved at compile time."""

    shape: str  # 'circular' | 'rectangular'
    n_st: int  # number of stations
    seg_nodes: Tuple[int, ...]  # strip-node count per segment (0-len segs get 1)
    seg_flat: Tuple[bool, ...]  # True where the segment has zero length
    cap_kinds: Tuple[str, ...]  # per cap: 'bottom' | 'top' | 'mid'
    pot_mod: bool
    mcf: bool
    type: int = 2
    name: str = ""

    @property
    def n_nodes(self) -> int:
        return 2 + sum(self.seg_nodes)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MemberGeometry:
    """Differentiable member description (all jnp arrays)."""

    rA0: jnp.ndarray  # [3] end A rel. PRP (heading already applied)
    rB0: jnp.ndarray  # [3]
    gamma: jnp.ndarray  # [] twist about member axis [deg]
    stations_frac: jnp.ndarray  # [n_st] along-axis positions as fractions 0..1
    d: jnp.ndarray  # [n_st] diameters (circ) or [n_st,2] side lengths (rect)
    t: jnp.ndarray  # [n_st] shell thickness
    l_fill_frac: jnp.ndarray  # [n_st-1] ballast fill per segment as fraction of length
    rho_fill: jnp.ndarray  # [n_st-1] ballast density per segment
    rho_shell: jnp.ndarray  # [] shell density
    Cd_q: jnp.ndarray  # [n_st]
    Cd_p1: jnp.ndarray
    Cd_p2: jnp.ndarray
    Cd_end: jnp.ndarray
    Ca_q: jnp.ndarray
    Ca_p1: jnp.ndarray
    Ca_p2: jnp.ndarray
    Ca_end: jnp.ndarray
    cap_stations_frac: jnp.ndarray  # [n_caps] along-axis position as fraction of length
    cap_t: jnp.ndarray  # [n_caps]
    cap_d_in: jnp.ndarray  # [n_caps] (circ) or [n_caps,2] (rect)


@dataclasses.dataclass(frozen=True)
class CompiledMember:
    topo: MemberTopology
    geom: MemberGeometry


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MemberPose:
    """Member orientation/placement derived from the platform pose."""

    R: jnp.ndarray  # [3,3] member DCM (global <- local)
    q: jnp.ndarray  # [3] axial unit vector
    p1: jnp.ndarray  # [3] transverse unit vector 1
    p2: jnp.ndarray  # [3] transverse unit vector 2
    rA: jnp.ndarray  # [3] displaced end A
    rB: jnp.ndarray  # [3] displaced end B
    r: jnp.ndarray  # [n_nodes,3] displaced node positions
    ls: jnp.ndarray  # [n_nodes] along-axis node positions
    dls: jnp.ndarray  # [n_nodes] lumped strip lengths
    ds: jnp.ndarray  # [n_nodes] (+[,2] rect) strip diameters / side lengths
    drs: jnp.ndarray  # [n_nodes] (+[,2] rect) strip radius change
    l: jnp.ndarray  # [] member length


# ---------------------------------------------------------------------------
# host-side design compilation
# ---------------------------------------------------------------------------


def compile_member(mi: dict, heading: float = 0.0, dls_max_default: float = 5.0) -> CompiledMember:
    """Parse one member description dict into (topology, geometry).

    Mirrors the input semantics of Member.__init__ (raft_member.py:16-220):
    station normalization to member length, heading rotation (with the
    vertical-member twist special case), scalar→array tiling of
    coefficients, ballast validation, and the dlsMax strip discretization
    — except the *node layout* (how many strips each segment gets) is
    frozen into the topology so downstream shapes are static.
    """
    name = str(mi.get("name", ""))
    mtype = int(mi.get("type", 2))

    rA0 = np.array(mi["rA"], dtype=float)
    rB0 = np.array(mi["rB"], dtype=float)
    if (rA0[2] == 0 or rB0[2] == 0) and mtype != 3:
        raise ValueError("Members cannot start or end on the waterplane")
    if rB0[2] < rA0[2]:
        rA0, rB0 = rB0.copy(), rA0.copy()

    shape = "circular" if str(mi["shape"])[0].lower() == "c" else (
        "rectangular" if str(mi["shape"])[0].lower() == "r" else None
    )
    if shape is None:
        raise ValueError("The only allowable shape strings are circular and rectangular")

    pot_mod = bool(get_from_dict(mi, "potMod", dtype=bool, default=False))
    mcf = bool(get_from_dict(mi, "MCF", dtype=bool, default=False))
    gamma = float(get_from_dict(mi, "gamma", default=0.0))

    rAB = rB0 - rA0
    length = float(np.linalg.norm(rAB))

    if heading != 0.0:
        c, s = np.cos(np.deg2rad(heading)), np.sin(np.deg2rad(heading))
        rot = np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]])
        rA0 = rot @ rA0
        rB0 = rot @ rB0
        if rAB[0] == 0.0 and rAB[1] == 0.0:  # vertical member: heading acts as twist
            gamma += heading

    st = np.array(mi["stations"], dtype=float)
    n = len(st)
    if n < 2:
        raise ValueError("At least two stations entries must be provided")
    if sorted(st.tolist()) != st.tolist():
        raise ValueError(f"Member {name}: the station list is not in ascending order.")
    stations_frac = (st - st[0]) / (st[-1] - st[0])
    stations = stations_frac * length

    if shape == "circular":
        d = get_from_dict(mi, "d", shape=n)
        gamma = 0.0  # twist is meaningless for circular sections
    else:
        d = get_from_dict(mi, "d", shape=[n, 2])
    if mcf and shape != "circular":
        mcf = False  # MacCamy-Fuchs only applies to circular members

    t = get_from_dict(mi, "t", shape=n)
    rho_shell = float(get_from_dict(mi, "rho_shell", shape=0, default=8500.0))

    st_fill = get_from_dict(mi, "l_fill", shape=n - 1, default=0)
    for i in range(n - 1):
        if st_fill[i] < 0:
            raise ValueError(f"Member {name}: ballast level in section {i + 1} is negative.")
        if st_fill[i] > st[i + 1] - st[i]:
            raise ValueError(
                f"Member {name}: ballast level in section {i + 1} exceeds section length."
            )
    l_fill_frac = st_fill / (st[-1] - st[0])

    rho_fill_in = get_from_dict(mi, "rho_fill", shape=-1, default=1025)
    if np.isscalar(rho_fill_in):
        rho_fill = np.zeros(n - 1) + rho_fill_in
    else:
        rho_fill = np.asarray(rho_fill_in, dtype=float)
        if len(rho_fill) != n - 1:
            raise ValueError(
                f"Member {name}: number of ballast densities must be one less than stations."
            )

    # ----- end caps / bulkheads: resolve which interpolation branch applies -----
    cap_st_in = get_from_dict(mi, "cap_stations", shape=-1, default=[])
    if np.isscalar(cap_st_in):
        cap_st_in = np.array([cap_st_in], dtype=float)
    n_caps = len(cap_st_in)
    if n_caps:
        cap_t = get_from_dict(mi, "cap_t", shape=n_caps)
        if shape == "circular":
            cap_d_in = get_from_dict(mi, "cap_d_in", shape=n_caps)
        else:
            cap_d_in = np.asarray(get_from_dict(mi, "cap_d_in", shape=-1), dtype=float)
            cap_d_in = np.broadcast_to(np.atleast_2d(cap_d_in), (n_caps, 2)).copy()
        cap_stations_frac_np = (np.asarray(cap_st_in, dtype=float) - st[0]) / (st[-1] - st[0])
        cap_stations = cap_stations_frac_np * length
        cap_kinds = []
        for i in range(n_caps):
            L, h = cap_stations[i], cap_t[i]
            if L == stations[0]:
                cap_kinds.append("bottom")
            elif L == stations[-1]:
                cap_kinds.append("top")
            elif (stations[0] < L < stations[0] + h) or (stations[-1] - h < L < stations[-1]):
                raise ValueError("Cap placement within a cap-thickness of the member end is unsupported")
            elif i < n_caps - 1 and L == cap_stations[i + 1]:
                # member discontinuity: paired caps at the same station —
                # this one closes the lower member going down
                cap_kinds.append("disc_down")
            elif i > 0 and L == cap_stations[i - 1]:
                # ... and this one closes the upper member going up
                cap_kinds.append("disc_up")
            else:
                cap_kinds.append("mid")
    else:
        cap_t = np.zeros(0)
        cap_d_in = np.zeros(0) if shape == "circular" else np.zeros((0, 2))
        cap_stations = np.zeros(0)
        cap_stations_frac_np = np.zeros(0)
        cap_kinds = []

    # coefficient tables (per station)
    Cd_q = get_from_dict(mi, "Cd_q", shape=n, default=0.0)
    Cd_p1 = get_from_dict(mi, "Cd", shape=n, default=0.6, index=0)
    Cd_p2 = get_from_dict(mi, "Cd", shape=n, default=0.6, index=1)
    Cd_end = get_from_dict(mi, "CdEnd", shape=n, default=0.6)
    Ca_q = get_from_dict(mi, "Ca_q", shape=n, default=0.0)
    Ca_p1 = get_from_dict(mi, "Ca", shape=n, default=0.97, index=0)
    Ca_p2 = get_from_dict(mi, "Ca", shape=n, default=0.97, index=1)
    Ca_end = get_from_dict(mi, "CaEnd", shape=n, default=0.6)

    # ----- freeze the strip-node layout (counts only; positions stay traced) -----
    dls_max = float(np.asarray(mi.get("dlsMax", dls_max_default)).reshape(-1)[0])
    seg_nodes = []
    seg_flat = []
    for i in range(1, n):
        lstrip = stations[i] - stations[i - 1]
        if lstrip > 0.0:
            seg_nodes.append(int(np.ceil(lstrip / dls_max)))
            seg_flat.append(False)
        else:
            seg_nodes.append(1)
            seg_flat.append(True)

    topo = MemberTopology(
        shape=shape,
        n_st=n,
        seg_nodes=tuple(seg_nodes),
        seg_flat=tuple(seg_flat),
        cap_kinds=tuple(cap_kinds),
        pot_mod=pot_mod,
        mcf=mcf,
        type=mtype,
        name=name,
    )
    geom = MemberGeometry(
        rA0=jnp.asarray(rA0),
        rB0=jnp.asarray(rB0),
        gamma=jnp.asarray(float(gamma)),
        stations_frac=jnp.asarray(stations_frac),
        d=jnp.asarray(d),
        t=jnp.asarray(t),
        l_fill_frac=jnp.asarray(l_fill_frac),
        rho_fill=jnp.asarray(rho_fill),
        rho_shell=jnp.asarray(rho_shell),
        Cd_q=jnp.asarray(Cd_q),
        Cd_p1=jnp.asarray(Cd_p1),
        Cd_p2=jnp.asarray(Cd_p2),
        Cd_end=jnp.asarray(Cd_end),
        Ca_q=jnp.asarray(Ca_q),
        Ca_p1=jnp.asarray(Ca_p1),
        Ca_p2=jnp.asarray(Ca_p2),
        Ca_end=jnp.asarray(Ca_end),
        cap_stations_frac=jnp.asarray(cap_stations_frac_np),
        cap_t=jnp.asarray(cap_t),
        cap_d_in=jnp.asarray(cap_d_in),
    )
    return CompiledMember(topo=topo, geom=geom)


# ---------------------------------------------------------------------------
# pose / discretization
# ---------------------------------------------------------------------------


def axis_length(geom: MemberGeometry):
    """Traced member length |rB0 - rA0| — the scale for all along-axis
    fractional coordinates (keeps end-position perturbations differentiable)."""
    return jnp.linalg.norm(geom.rB0 - geom.rA0)


def _safe_norm2(x, y):
    """sqrt(x²+y²) with a well-defined (zero) gradient at the origin —
    vertical members are the common case and d(sqrt)/dx at 0 is inf."""
    s = x * x + y * y
    return jnp.where(s > 0, jnp.sqrt(jnp.where(s > 0, s, 1.0)), 0.0)


def _discretize(topo: MemberTopology, geom: MemberGeometry):  # graftlint: static=topo
    """Strip discretization with the reference's node layout
    (raft_member.py:169-216), node counts static from the topology.
    Builds one vectorized block per segment and concatenates (a handful of
    ops per segment rather than per node)."""
    st = geom.stations_frac * axis_length(geom)
    d = geom.d
    rect = topo.shape == "rectangular"
    zero = jnp.zeros((1,), dtype=st.dtype)

    ls_parts = [zero]
    dls_parts = [zero]
    ds_parts = [(0.5 * d[0])[None]]
    drs_parts = [(0.5 * d[0])[None]]

    for i in range(1, topo.n_st):
        lstrip = st[i] - st[i - 1]
        if not topo.seg_flat[i - 1]:
            ns = topo.seg_nodes[i - 1]
            dlstrip = lstrip / ns
            m = 0.5 * (d[i] - d[i - 1]) / lstrip
            j = jnp.arange(ns, dtype=st.dtype) + 0.5
            ls_parts.append(st[i - 1] + dlstrip * j)
            dls_parts.append(jnp.broadcast_to(dlstrip, (ns,)))
            if rect:
                ds_parts.append(d[i - 1][None, :] + (dlstrip * j)[:, None] * 2 * m[None, :])
                drs_parts.append(jnp.broadcast_to(dlstrip * m, (ns, 2)))
            else:
                ds_parts.append(d[i - 1] + dlstrip * 2 * m * j)
                drs_parts.append(jnp.broadcast_to(dlstrip * m, (ns,)))
        else:
            ls_parts.append(st[i - 1][None])
            dls_parts.append(zero)
            ds_parts.append((0.5 * (d[i - 1] + d[i]))[None])
            drs_parts.append((0.5 * (d[i] - d[i - 1]))[None])

    ls_parts.append(st[-1][None])
    dls_parts.append(zero)
    ds_parts.append((0.5 * d[-1])[None])
    drs_parts.append((-0.5 * d[-1])[None])

    return (
        jnp.concatenate(ls_parts),
        jnp.concatenate(dls_parts),
        jnp.concatenate(ds_parts),
        jnp.concatenate(drs_parts),
    )


def member_pose(topo: MemberTopology, geom: MemberGeometry, r6=None) -> MemberPose:
    """Member orientation and node positions under platform pose ``r6``.

    Parity with Member.setPosition (raft_member.py:245-304): Z1Y2Z3
    intrinsic Euler construction from the member axis + twist gamma, then
    platform rotation/translation applied on top.
    """
    if r6 is None:
        r6 = jnp.zeros(6)
    r6 = jnp.asarray(r6)

    rAB0 = geom.rB0 - geom.rA0
    length = jnp.linalg.norm(rAB0)
    q0 = rAB0 / length

    beta = jnp.arctan2(q0[1], q0[0])
    phi = jnp.arctan2(_safe_norm2(q0[0], q0[1]), q0[2])
    s1, c1 = jnp.sin(beta), jnp.cos(beta)
    s2, c2 = jnp.sin(phi), jnp.cos(phi)
    g = jnp.deg2rad(geom.gamma)
    s3, c3 = jnp.sin(g), jnp.cos(g)

    R0 = jnp.array(
        [
            [c1 * c2 * c3 - s1 * s3, -c3 * s1 - c1 * c2 * s3, c1 * s2],
            [c1 * s3 + c2 * c3 * s1, c1 * c3 - c2 * s1 * s3, s1 * s2],
            [-c3 * s2, s2 * s3, c2],
        ]
    )
    p1_0 = R0 @ jnp.array([1.0, 0.0, 0.0])

    R_pl = transforms.rotation_matrix(r6[3:])
    R = R_pl @ R0
    q = R_pl @ q0
    p1 = R_pl @ p1_0
    p2 = jnp.cross(q, p1)

    rA = transforms.transform_position(geom.rA0, r6)
    rB = transforms.transform_position(geom.rB0, r6)

    ls, dls, ds, drs = _discretize(topo, geom)
    r = rA + (ls / length)[:, None] * (rB - rA)

    return MemberPose(R=R, q=q, p1=p1, p2=p2, rA=rA, rB=rB, r=r, ls=ls, dls=dls, ds=ds, drs=drs, l=length)


# ---------------------------------------------------------------------------
# inertia
# ---------------------------------------------------------------------------


def _segment_mass_props(topo: MemberTopology, geom: MemberGeometry):
    """Pose-independent per-segment masses, centroids, and local MoIs
    (the per-submember section of Member.getInertia, raft_member.py:416-526).
    Returns arrays over the n_st-1 segments."""
    st = geom.stations_frac * axis_length(geom)
    lseg = st[1:] - st[:-1]  # [n_seg]
    rho_sh = geom.rho_shell
    lf = geom.l_fill_frac * axis_length(geom)
    rf = geom.rho_fill
    nonzero = lseg > 0
    lsafe = jnp.where(nonzero, lseg, 1.0)

    if topo.shape == "circular":
        dA, dB = geom.d[:-1], geom.d[1:]
        dAi = dA - 2 * geom.t[:-1]
        dBi = dB - 2 * geom.t[1:]
        V_outer, hco = frustum.frustum_vcv_circ(dA, dB, lseg)
        V_inner, hci = frustum.frustum_vcv_circ(dAi, dBi, lseg)
        dBi_fill = (dBi - dAi) * (lf / lsafe) + dAi
        v_fill, hc_fill = frustum.frustum_vcv_circ(dAi, dBi_fill, lf)
        I_rad_o, I_ax_o = frustum.frustum_moi_circ(dA, dB, lseg, rho_sh)
        I_rad_i, I_ax_i = frustum.frustum_moi_circ(dAi, dBi, lseg, rho_sh)
        I_rad_f, I_ax_f = frustum.frustum_moi_circ(dAi, dBi_fill, lf, rf)
        circ = True
    else:
        slA, slB = geom.d[:-1], geom.d[1:]
        slAi = slA - 2 * geom.t[:-1, None]
        slBi = slB - 2 * geom.t[1:, None]
        V_outer, hco = frustum.frustum_vcv_rect(slA, slB, lseg)
        V_inner, hci = frustum.frustum_vcv_rect(slAi, slBi, lseg)
        slBi_fill = (slBi - slAi) * (lf / lsafe)[:, None] + slAi
        v_fill, hc_fill = frustum.frustum_vcv_rect(slAi, slBi_fill, lf)
        Ixx_o, Iyy_o, Izz_o = frustum.frustum_moi_rect(slA, slB, lseg, rho_sh)
        Ixx_i, Iyy_i, Izz_i = frustum.frustum_moi_rect(slAi, slBi, lseg, rho_sh)
        Ixx_f, Iyy_f, Izz_f = frustum.frustum_moi_rect(slAi, slBi_fill, lf, rf)
        circ = False

    v_shell = V_outer - V_inner
    m_shell = v_shell * rho_sh
    vsafe = jnp.where(v_shell > 0, v_shell, 1.0)
    hc_shell = (hco * V_outer - hci * V_inner) / vsafe
    m_fill = v_fill * rf
    mass = m_shell + m_fill
    msafe = jnp.where(mass > 0, mass, 1.0)
    hc = (hc_fill * m_fill + hc_shell * m_shell) / msafe

    if circ:
        I_rad_end = (I_rad_o - I_rad_i) + I_rad_f
        I_rad = I_rad_end - mass * hc**2
        I_ax = (I_ax_o - I_ax_i) + I_ax_f
        Ixx = Iyy = I_rad
        Izz = I_ax
    else:
        Ixx_end = (Ixx_o - Ixx_i) + Ixx_f
        Iyy_end = (Iyy_o - Iyy_i) + Iyy_f
        Izz = (Izz_o - Izz_i) + Izz_f
        Ixx = Ixx_end - mass * hc**2
        Iyy = Iyy_end - mass * hc**2

    # zero-length segments contribute nothing
    z = nonzero
    mass = jnp.where(z, mass, 0.0)
    m_shell = jnp.where(z, m_shell, 0.0)
    m_fill = jnp.where(z, m_fill, 0.0)
    v_fill = jnp.where(z, v_fill, 0.0)
    hc = jnp.where(z, hc, 0.0)
    Ixx = jnp.where(z, Ixx, 0.0)
    Iyy = jnp.where(z, Iyy, 0.0)
    Izz = jnp.where(z, Izz, 0.0)
    return mass, hc, m_shell, m_fill, v_fill, Ixx, Iyy, Izz


def _cap_mass_props(topo: MemberTopology, geom: MemberGeometry):  # graftlint: static=topo
    """Pose-independent cap/bulkhead masses and local MoIs
    (raft_member.py:553-671).  Branches are static via topo.cap_kinds."""
    masses, hcs, Ixxs, Iyys, Izzs, Ls, hs = [], [], [], [], [], [], []
    circ = topo.shape == "circular"
    st = geom.stations_frac * axis_length(geom)
    d_in_profile = geom.d - (2 * geom.t if circ else 2 * geom.t[:, None])

    def interp_profile(x):
        if circ:
            return jnp.interp(x, st, d_in_profile)
        return jnp.stack([jnp.interp(x, st, d_in_profile[:, 0]), jnp.interp(x, st, d_in_profile[:, 1])])

    for i, kind in enumerate(topo.cap_kinds):
        L = geom.cap_stations_frac[i] * axis_length(geom)
        h = geom.cap_t[i]
        hole = geom.cap_d_in[i]
        if kind == "bottom":
            dA = d_in_profile[0]
            dB = interp_profile(L + h)
            dAi = hole
            dBi = dB * (dAi / dA)
        elif kind == "top":
            dA = interp_profile(L - h)
            dB = d_in_profile[-1]
            dBi = hole
            dAi = dA * (dBi / dB)
        elif kind == "disc_down":
            # paired cap at a member discontinuity, closing downward; note
            # the reference indexes the diameter profile by *cap* index
            # here (raft_member.py:582-586) — reproduced as-is
            dA = interp_profile(L - h)
            dB = d_in_profile[i]
            dBi = hole
            dAi = dA * (dBi / dB)
        elif kind == "disc_up":
            dA = d_in_profile[i]
            dB = interp_profile(L + h)
            dAi = hole
            dBi = dB * (dAi / dA)
        else:  # mid bulkhead
            dA = interp_profile(L - h / 2)
            dB = interp_profile(L + h / 2)
            dM = interp_profile(L)
            dAi = dA * (hole / dM)
            dBi = dB * (hole / dM)

        if circ:
            V_o, hco = frustum.frustum_vcv_circ(dA, dB, h)
            V_i, hci = frustum.frustum_vcv_circ(dAi, dBi, h)
            I_rad_o, I_ax_o = frustum.frustum_moi_circ(dA, dB, h, geom.rho_shell)
            I_rad_i, I_ax_i = frustum.frustum_moi_circ(dAi, dBi, h, geom.rho_shell)
            v_cap = V_o - V_i
            m_cap = v_cap * geom.rho_shell
            hc_cap = (hco * V_o - hci * V_i) / jnp.where(v_cap > 0, v_cap, 1.0)
            I_rad = (I_rad_o - I_rad_i) - m_cap * hc_cap**2
            Ixx = Iyy = I_rad
            Izz = I_ax_o - I_ax_i
        else:
            V_o, hco = frustum.frustum_vcv_rect(dA, dB, h)
            V_i, hci = frustum.frustum_vcv_rect(dAi, dBi, h)
            Ixx_o, Iyy_o, Izz_o = frustum.frustum_moi_rect(dA, dB, h, geom.rho_shell)
            Ixx_i, Iyy_i, Izz_i = frustum.frustum_moi_rect(dAi, dBi, h, geom.rho_shell)
            v_cap = V_o - V_i
            m_cap = v_cap * geom.rho_shell
            hc_cap = (hco * V_o - hci * V_i) / jnp.where(v_cap > 0, v_cap, 1.0)
            Ixx = (Ixx_o - Ixx_i) - m_cap * hc_cap**2
            Iyy = (Iyy_o - Iyy_i) - m_cap * hc_cap**2
            Izz = Izz_o - Izz_i

        masses.append(m_cap)
        hcs.append(hc_cap)
        Ixxs.append(Ixx)
        Iyys.append(Iyy)
        Izzs.append(Izz)
        Ls.append(L)
        hs.append(h)

    if not masses:
        zero = jnp.zeros(0)
        return zero, zero, zero, zero, zero, zero, zero
    return (
        jnp.stack(masses),
        jnp.stack(hcs),
        jnp.stack(Ixxs),
        jnp.stack(Iyys),
        jnp.stack(Izzs),
        jnp.stack(Ls),
        jnp.stack(hs),
    )


def member_inertia(topo: MemberTopology, geom: MemberGeometry, pose: MemberPose, rPRP=None):  # graftlint: static=topo
    """Member mass/inertia rollup about the PRP in global directions.

    Returns (M_struc [6,6], mass, center [3], m_shell, m_fill [n_seg],
    rho_fill [n_seg]) with the same semantics as Member.getInertia
    (raft_member.py:307-707): per-segment local MoI rotated by the member
    DCM and translated to the PRP, caps included in the shell mass.
    """
    if rPRP is None:
        rPRP = jnp.zeros(3)
    rPRP = jnp.asarray(rPRP)

    mass_s, hc_s, mshell_s, mfill_s, vfill_s, Ixx_s, Iyy_s, Izz_s = _segment_mass_props(topo, geom)
    st = geom.stations_frac * axis_length(geom)

    # segment CG positions rel. PRP, global orientation
    centers = pose.rA + pose.q[None, :] * (st[:-1] + hc_s)[:, None] - rPRP

    def seg_matrix(mass, Ixx, Iyy, Izz, center):
        Mmat = jnp.diag(jnp.array([mass, mass, mass, 0.0, 0.0, 0.0]))
        I = jnp.diag(jnp.stack([Ixx, Iyy, Izz]))
        I_rot = pose.R @ I @ pose.R.T
        Mmat = Mmat.at[3:, 3:].set(I_rot)
        return transforms.translate_matrix_6to6(Mmat, center)

    M_segs = jax.vmap(seg_matrix)(mass_s, Ixx_s, Iyy_s, Izz_s, centers)
    M_struc = jnp.sum(M_segs, axis=0)
    mass_center = jnp.sum(mass_s[:, None] * centers, axis=0)
    m_shell_tot = jnp.sum(mshell_s)

    # caps
    m_c, hc_c, Ixx_c, Iyy_c, Izz_c, L_c, h_c = _cap_mass_props(topo, geom)
    if m_c.shape[0]:
        pos_caps = pose.rA + pose.q[None, :] * L_c[:, None] - rPRP
        offs = []
        for i, kind in enumerate(topo.cap_kinds):
            if kind == "bottom":
                offs.append(hc_c[i])
            elif kind == "top":
                offs.append(-(h_c[i] - hc_c[i]))
            else:
                offs.append(-(h_c[i] / 2 - hc_c[i]))
        centers_c = pos_caps + pose.q[None, :] * jnp.stack(offs)[:, None]
        M_caps = jax.vmap(seg_matrix)(m_c, Ixx_c, Iyy_c, Izz_c, centers_c)
        M_struc = M_struc + jnp.sum(M_caps, axis=0)
        mass_center = mass_center + jnp.sum(m_c[:, None] * centers_c, axis=0)
        m_shell_tot = m_shell_tot + jnp.sum(m_c)

    mass = M_struc[0, 0]
    center = mass_center / jnp.where(mass > 0, mass, 1.0)
    return M_struc, mass, center, m_shell_tot, mfill_s, geom.rho_fill


# ---------------------------------------------------------------------------
# hydrostatics
# ---------------------------------------------------------------------------


def member_hydrostatics(topo: MemberTopology, geom: MemberGeometry, pose: MemberPose, rPRP=None,
                        rho=RHO_WATER, g=GRAVITY):
    """Buoyancy force vector, hydrostatic stiffness, and waterplane props.

    Vectorized Member.getHydrostatics (raft_member.py:712-874): all
    segments are evaluated for all three submergence cases and combined
    with masks; waterplane quantities come from the (last) crossing
    segment like the reference's overwrite semantics.
    Returns (Fvec [6], Cmat [6,6], V_UW, r_center [3], AWP, IWP, xWP, yWP).
    """
    if rPRP is None:
        rPRP = jnp.zeros(3)
    rPRP = jnp.asarray(rPRP)
    st = geom.stations_frac * axis_length(geom)
    q = pose.q
    circ = topo.shape == "circular"

    rHS_ref = jnp.array([rPRP[0], rPRP[1], 0.0])
    rA_seg = pose.rA + q[None, :] * st[:-1, None] - rHS_ref  # [n_seg,3]
    rB_seg = pose.rA + q[None, :] * st[1:, None] - rHS_ref

    zA, zB = rA_seg[:, 2], rB_seg[:, 2]
    crossing = zA * zB <= 0
    submerged = (~crossing) & (zA <= 0) & (zB <= 0)

    beta = jnp.arctan2(q[1], q[0])
    phi = jnp.arctan2(_safe_norm2(q[0], q[1]), q[2])
    cosPhi, sinPhi, tanPhi = jnp.cos(phi), jnp.sin(phi), jnp.tan(phi)
    cosBeta, sinBeta = jnp.cos(beta), jnp.sin(beta)

    dz = jnp.where(jnp.abs(zB - zA) > 0, zB - zA, 1.0)
    # interpolation factor to the waterplane, clamped so non-crossing
    # segments can't extrapolate to negative side lengths (sqrt(A1*A2) in
    # the rectangular frustum would turn that into NaN that survives the
    # 0-weight mask)
    fWP = jnp.clip((0.0 - zA) / dz, 0.0, 1.0)
    xWP_seg = rA_seg[:, 0] + fWP * (rB_seg[:, 0] - rA_seg[:, 0])
    yWP_seg = rA_seg[:, 1] + fWP * (rB_seg[:, 1] - rA_seg[:, 1])

    # NOTE the reference interpolates the waterplane diameter with the
    # station order swapped (d[i] at zA, d[i-1] at zB; raft_member.py:769)
    # — reproduced verbatim since golden values embed it.
    if circ:
        dWP = geom.d[1:] + fWP * (geom.d[:-1] - geom.d[1:])
        AWP_seg = (jnp.pi / 4) * dWP**2
        IWP_seg = (jnp.pi / 64) * dWP**4
        IxWP_seg = IWP_seg
        IyWP_seg = IWP_seg
    else:
        slWP = geom.d[1:] + fWP[:, None] * (geom.d[:-1] - geom.d[1:])
        AWP_seg = slWP[:, 0] * slWP[:, 1]
        IxWP_l = (1.0 / 12.0) * slWP[:, 0] * slWP[:, 1] ** 3
        IyWP_l = (1.0 / 12.0) * slWP[:, 0] ** 3 * slWP[:, 1]

        def rot_wp(ix, iy):
            I = jnp.diag(jnp.stack([ix, iy, jnp.zeros_like(ix)]))
            I_rot = pose.R @ I @ pose.R.T
            return I_rot[0, 0], I_rot[1, 1]

        IxWP_seg, IyWP_seg = jax.vmap(rot_wp)(IxWP_l, IyWP_l)
        # the reference only assigns the returned IWP in the circular branch
        # (raft_member.py:771); rectangular members report IWP = 0
        IWP_seg = jnp.zeros_like(AWP_seg)
        dWP = None

    cosSafe = jnp.where(jnp.abs(cosPhi) > 1e-12, cosPhi, 1e-12)
    LWP = jnp.abs(zA / cosSafe)

    # ---- partially submerged (crossing) case ----
    if circ:
        V_cross, hc_cross = frustum.frustum_vcv_circ(geom.d[:-1], dWP, LWP)
    else:
        V_cross, hc_cross = frustum.frustum_vcv_rect(geom.d[:-1], slWP, LWP)
    r_center_cross = rA_seg + q[None, :] * hc_cross[:, None]

    dPhi_dThx = -sinBeta
    dPhi_dThy = cosBeta
    Fz_cross = rho * g * V_cross
    if circ:
        M = -rho * g * jnp.pi * (dWP**2 / 32 * (2.0 + tanPhi**2) + 0.5 * (zA / cosSafe) ** 2) * sinPhi
    else:
        M = jnp.zeros_like(Fz_cross)
    Mx_cross = M * dPhi_dThx
    My_cross = M * dPhi_dThy

    # ---- fully submerged case ----
    lseg = st[1:] - st[:-1]
    if circ:
        V_sub, hc_sub = frustum.frustum_vcv_circ(geom.d[:-1], geom.d[1:], lseg)
    else:
        V_sub, hc_sub = frustum.frustum_vcv_rect(geom.d[:-1], geom.d[1:], lseg)
    r_center_sub = rA_seg + q[None, :] * hc_sub[:, None]

    # ---- combine with masks ----
    cross_f = crossing.astype(st.dtype)
    sub_f = submerged.astype(st.dtype)

    Fvec = jnp.zeros(6, dtype=st.dtype)
    Fvec = Fvec.at[2].add(jnp.sum(cross_f * Fz_cross))
    Fvec = Fvec.at[3].add(jnp.sum(cross_f * (Mx_cross + Fz_cross * rA_seg[:, 1])))
    Fvec = Fvec.at[4].add(jnp.sum(cross_f * (My_cross - Fz_cross * rA_seg[:, 0])))

    F_sub = transforms.translate_force_3to6(
        jnp.stack([jnp.zeros_like(V_sub), jnp.zeros_like(V_sub), rho * g * V_sub], axis=-1),
        r_center_sub,
    )  # [n_seg, 6]
    Fvec = Fvec + jnp.sum(sub_f[:, None] * F_sub, axis=0)

    Cmat = jnp.zeros((6, 6), dtype=st.dtype)
    dFz_dz = -rho * g * AWP_seg / cosSafe
    Cmat = Cmat.at[2, 2].add(jnp.sum(cross_f * (-dFz_dz)))
    Cmat = Cmat.at[2, 3].add(jnp.sum(cross_f * rho * g * (-AWP_seg * yWP_seg)))
    Cmat = Cmat.at[2, 4].add(jnp.sum(cross_f * rho * g * (AWP_seg * xWP_seg)))
    Cmat = Cmat.at[3, 2].add(jnp.sum(cross_f * rho * g * (-AWP_seg * yWP_seg)))
    Cmat = Cmat.at[3, 3].add(jnp.sum(cross_f * rho * g * (IxWP_seg + AWP_seg * yWP_seg**2)))
    Cmat = Cmat.at[3, 4].add(jnp.sum(cross_f * rho * g * (AWP_seg * xWP_seg * yWP_seg)))
    Cmat = Cmat.at[4, 2].add(jnp.sum(cross_f * rho * g * (AWP_seg * xWP_seg)))
    Cmat = Cmat.at[4, 3].add(jnp.sum(cross_f * rho * g * (AWP_seg * xWP_seg * yWP_seg)))
    Cmat = Cmat.at[4, 4].add(jnp.sum(cross_f * rho * g * (IyWP_seg + AWP_seg * xWP_seg**2)))
    Cmat = Cmat.at[3, 3].add(jnp.sum(cross_f * rho * g * V_cross * r_center_cross[:, 2]))
    Cmat = Cmat.at[4, 4].add(jnp.sum(cross_f * rho * g * V_cross * r_center_cross[:, 2]))
    Cmat = Cmat.at[3, 3].add(jnp.sum(sub_f * rho * g * V_sub * r_center_sub[:, 2]))
    Cmat = Cmat.at[4, 4].add(jnp.sum(sub_f * rho * g * V_sub * r_center_sub[:, 2]))

    V_UW = jnp.sum(cross_f * V_cross + sub_f * V_sub)
    r_centerV = jnp.sum(
        (cross_f * V_cross)[:, None] * r_center_cross + (sub_f * V_sub)[:, None] * r_center_sub, axis=0
    )
    r_center = jnp.where(V_UW > 0, r_centerV / jnp.where(V_UW > 0, V_UW, 1.0), jnp.zeros(3))

    # waterplane properties: reference keeps the LAST crossing segment's values
    any_cross = jnp.any(crossing)
    n_seg = st.shape[0] - 1
    idx_last = (n_seg - 1) - jnp.argmax(crossing[::-1])
    AWP = jnp.where(any_cross, AWP_seg[idx_last], 0.0)
    IWP = jnp.where(any_cross, IWP_seg[idx_last], 0.0)
    xWP = jnp.where(any_cross, xWP_seg[idx_last], 0.0)
    yWP = jnp.where(any_cross, yWP_seg[idx_last], 0.0)

    return Fvec, Cmat, V_UW, r_center, AWP, IWP, xWP, yWP


# ---------------------------------------------------------------------------
# strip-theory hydrodynamic coefficients (Morison added mass + FK excitation)
# ---------------------------------------------------------------------------


def node_coefficients(geom: MemberGeometry, pose: MemberPose):
    """Per-node drag/added-mass coefficients, linearly interpolated in
    along-axis position over the station tables (as np.interp does in
    raft_member.py:916-919)."""
    st = geom.stations_frac * axis_length(geom)

    def it(tab):
        return jnp.interp(pose.ls, st, tab)

    return {
        "Cd_q": it(geom.Cd_q),
        "Cd_p1": it(geom.Cd_p1),
        "Cd_p2": it(geom.Cd_p2),
        "Cd_end": it(geom.Cd_end),
        "Ca_q": it(geom.Ca_q),
        "Ca_p1": it(geom.Ca_p1),
        "Ca_p2": it(geom.Ca_p2),
        "Ca_end": it(geom.Ca_end),
    }


def node_volumes_areas(topo: MemberTopology, pose: MemberPose):
    """Per-node side volumes (with free-surface clipping), end volumes and
    signed end areas (raft_member.py:922-950), plus the drag reference
    areas used by the linearization (raft_fowt.py:1198-1238)."""
    circ = topo.shape == "circular"
    ds, drs, dls = pose.ds, pose.drs, pose.dls
    z = pose.r[:, 2]

    if circ:
        v_side = 0.25 * jnp.pi * ds**2 * dls
        v_end = jnp.pi / 12.0 * jnp.abs((ds + drs) ** 3 - (ds - drs) ** 3)
        a_end = jnp.pi * ds * drs
        a_drag_q = jnp.pi * ds * dls
        a_drag_p1 = ds * dls
        a_drag_p2 = ds * dls
    else:
        v_side = ds[:, 0] * ds[:, 1] * dls
        dm_p = jnp.mean(ds + drs, axis=-1)
        dm_m = jnp.mean(ds - drs, axis=-1)
        v_end = jnp.pi / 12.0 * (dm_p**3 - dm_m**3)
        a_end = (ds[:, 0] + drs[:, 0]) * (ds[:, 1] + drs[:, 1]) - (ds[:, 0] - drs[:, 0]) * (
            ds[:, 1] - drs[:, 1]
        )
        # NOTE: the reference's rectangular axial drag area doubles ds[0]
        # (2*(ds0+ds0); raft_fowt.py:1200) — kept for parity
        a_drag_q = 2 * (ds[:, 0] + ds[:, 0]) * dls
        a_drag_p1 = ds[:, 0] * dls
        a_drag_p2 = ds[:, 1] * dls

    # free-surface volume clipping for strips poking above z=0
    dls_safe = jnp.where(dls > 0, dls, 1.0)
    clip = jnp.where(z + 0.5 * dls > 0, (0.5 * dls - z) / dls_safe, 1.0)
    v_side = v_side * clip

    return {
        "v_side": v_side,
        "v_end": v_end,
        "a_end": a_end,
        "a_drag_q": a_drag_q,
        "a_drag_p1": a_drag_p1,
        "a_drag_p2": a_drag_p2,
    }


def member_hydro_constants(topo: MemberTopology, geom: MemberGeometry, pose: MemberPose,  # graftlint: static=topo
                           r_ref=None, rho=RHO_WATER, g=GRAVITY, k_array=None):
    """Strip-theory added-mass and inertial-excitation coefficients.

    Parity with Member.calcHydroConstants + calcImat + getCmSides
    (raft_member.py:877-1088).  Returns a dict with per-node ``Amat``
    [NN,3,3], ``Imat`` [NN,3,3] (plus ``Imat_mcf`` [NN,3,3,nw] complex if
    ``k_array`` given and the member is MCF-flagged), signed end areas
    ``a_i`` [NN], and the 6x6 rollups ``A_hydro``/``I_hydro`` about
    ``r_ref``.  potMod members produce zeros (their loads come from BEM).
    """
    if r_ref is None:
        r_ref = jnp.zeros(3)
    r_ref = jnp.asarray(r_ref)

    c = node_coefficients(geom, pose)
    va = node_volumes_areas(topo, pose)

    wet = pose.r[:, 2] < 0
    if topo.pot_mod:  # potential-flow members carry no strip-theory loads
        wet = jnp.zeros_like(wet)

    qM = transforms.outer3(pose.q)
    p1M = transforms.outer3(pose.p1)
    p2M = transforms.outer3(pose.p2)

    wet_f = wet.astype(pose.ls.dtype)
    v_side = va["v_side"] * wet_f
    v_end = va["v_end"] * wet_f
    a_i = va["a_end"] * wet_f

    Amat = (
        rho * v_side[:, None, None] * (c["Ca_p1"][:, None, None] * p1M + c["Ca_p2"][:, None, None] * p2M)
        + rho * v_end[:, None, None] * c["Ca_end"][:, None, None] * qM
    )
    Imat_end = rho * v_end[:, None, None] * c["Ca_end"][:, None, None] * qM
    Imat = (
        rho
        * v_side[:, None, None]
        * ((1.0 + c["Ca_p1"])[:, None, None] * p1M + (1.0 + c["Ca_p2"])[:, None, None] * p2M)
        + Imat_end
    )

    offs = pose.r - r_ref
    A_hydro = jnp.sum(transforms.translate_matrix_3to6(Amat, offs), axis=0)
    I_hydro = jnp.sum(transforms.translate_matrix_3to6(Imat, offs), axis=0)

    out = {"Amat": Amat, "Imat": Imat, "a_i": a_i, "A_hydro": A_hydro, "I_hydro": I_hydro}

    if k_array is not None and topo.mcf:
        out["Imat_mcf"] = _imat_mcf(topo, geom, pose, c, v_side, Imat_end, jnp.asarray(k_array), rho)
    return out


def _imat_mcf(topo, geom, pose, c, v_side, Imat_end, k_array, rho):
    """Frequency-dependent complex FK matrix with the MacCamy-Fuchs Cm
    (raft_member.py:1017-1048, 1053-1088), including the smooth short-wave
    ramp between the Morison Cm and the MCF value."""
    from ..ops import bessel

    R = pose.ds / 2.0  # [NN] node radii (circular only — MCF gated on that)
    kR = k_array[None, :] * R[:, None]  # [NN, nw]
    kR_safe = jnp.where(kR > 0, kR, 1e-12)
    Hp1 = 0.5 * (bessel.hankel1(0, kR_safe) - bessel.hankel1(2, kR_safe))
    Cm_mcf = 4j / (jnp.pi * kR_safe**2 * Hp1)

    Cm0_p1 = 1.0 + c["Ca_p1"]
    Cm0_p2 = 1.0 + c["Ca_p2"]

    R_safe = jnp.where(R > 0, R, 1.0)
    Tr = jnp.pi / 5.0 / R_safe  # [NN] threshold wavenumber (λ/D = 5)
    k_b = k_array[None, :]
    ramp = jnp.where(
        k_b <= 0.0,
        0.0,
        jnp.where(k_b < Tr[:, None], 0.5 * (1 - jnp.cos(jnp.pi * k_b / Tr[:, None])), 1.0),
    )

    Cm_p1 = Cm_mcf * ramp + Cm0_p1[:, None] * (1 - ramp)
    Cm_p2 = Cm_mcf * ramp + Cm0_p2[:, None] * (1 - ramp)

    p1M = transforms.outer3(pose.p1)
    p2M = transforms.outer3(pose.p2)
    # [NN,3,3,nw]
    sides = rho * v_side[:, None, None, None] * (
        Cm_p1[:, None, None, :] * p1M[None, :, :, None] + Cm_p2[:, None, None, :] * p2M[None, :, :, None]
    )
    return sides + Imat_end[:, :, :, None]


# ---------------------------------------------------------------------------
# jit caching
# ---------------------------------------------------------------------------
# The host Model layer calls these kernels per member, per Newton/drag
# iteration; eagerly that is hundreds of tiny device dispatches per call
# (~50 ms/member measured on CPU).  The topology is hashable and frozen,
# so wrapping each kernel in jit with the topology static gives automatic
# per-(topology, shapes) trace caching: the first call per topology
# compiles one fused kernel, every later call — across drag iterations,
# Newton steps, and design-sweep variants — is a cache hit.  vmap/grad
# trace straight through the jit wrappers, so the batched design compiler
# (parallel.design_batch) composes with them unchanged.

member_pose = jax.jit(member_pose, static_argnums=0)
member_inertia = jax.jit(member_inertia, static_argnums=0)
member_hydrostatics = jax.jit(member_hydrostatics, static_argnums=0)
member_hydro_constants = jax.jit(member_hydro_constants, static_argnums=0)
node_coefficients = jax.jit(node_coefficients)
node_volumes_areas = jax.jit(node_volumes_areas, static_argnums=0)
