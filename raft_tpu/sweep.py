"""Design-sweep execution layer (parametersweep-equivalent, batched).

The reference sweeps designs with serial nested for-loops re-running
the full model per point (raft/parametersweep.py:56-100) — its prime
TPU-sharding target (SURVEY.md §2.3).  Here a sweep runs as:

1.  host loop compiling each design variant (geometry changes, same
    topology → identical trace shapes, so the jitted case solver is
    compiled ONCE and reused across all variants);
2.  per design, the sea-state batch solves as one vmapped, mesh-sharded
    device call (raft_tpu.parallel.CaseBatch);
3.  response statistics reduce on device.

``sweep`` mirrors the reference's mutate-design-dict pattern: you give
a base design, a list of (path, values) axes, and get the full factorial
grid of metrics.
"""

from __future__ import annotations

import copy
import itertools

import numpy as np
import jax
import jax.numpy as jnp

from .core.model import Model
from .ops import waves


def set_in_design(design, path, value):
    """Set a nested design-dict entry; path like
    'platform.members.0.d' or a callable(design, value)."""
    if callable(path):
        path(design, value)
        return
    keys = path.split(".")
    node = design
    for k in keys[:-1]:
        node = node[int(k)] if k.lstrip("-").isdigit() else node[k]
    last = keys[-1]
    if last.lstrip("-").isdigit():
        node[int(last)] = value
    else:
        node[last] = value


def _compile_variant(base_design, axes, combo, device):
    from .parallel.case_solve import design_params

    design = copy.deepcopy(base_design)
    for (path, _), val in zip(axes, combo):
        set_in_design(design, path, val)
    model = Model(design)
    fowt = model.fowtList[0]
    fowt.setPosition(np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0]))
    fowt.calcStatics()
    fowt.calcHydroConstants()
    p, s = design_params(fowt, include_aero=False, device=device)
    return p, s, fowt


def sweep(base_design, axes, sea_states, n_iter=15, device=None, display=0,
          checkpoint=None, chunk_size=256):
    """Run a factorial design sweep.

    Parameters
    ----------
    base_design : dict
        RAFT design dict (strip-theory configuration).
    axes : list of (path_or_callable, values)
        Design-variable axes; full factorial product is evaluated.
    sea_states : list of (Hs, Tp) or (Hs, Tp, heading_deg)
        Wave cases solved (batched) for every design variant.
    checkpoint : str, optional
        Path to an .npz progress file.  Designs execute in chunks of
        ``chunk_size``; after each chunk the partial results are saved
        (atomically), and a re-run of the same sweep resumes from the
        first unfinished chunk — the sweep-level resumability SURVEY.md
        §5 calls for (the reference's serial sweep restarts from scratch).
        A checkpoint from a *different* sweep signature is ignored.

    Returns
    -------
    dict with 'grid' (the factorial list of value tuples) and
    'motion_std' [n_designs, n_cases, 6] motion standard deviations.
    """
    import hashlib
    import os

    from .parallel.case_solve import make_parametric_solver

    combos = list(itertools.product(*[v for _, v in axes]))
    n_designs = len(combos)
    n_cases = len(sea_states)
    grid = combos

    results = np.full((n_designs, n_cases, 6), np.nan)
    done = np.zeros(n_designs, dtype=bool)
    sig = None
    if checkpoint:
        # checkpoint identity covers the whole sweep definition: base
        # design, axis PATHS (a callable axis repr includes a per-process
        # address, so such sweeps conservatively never resume), exact
        # value bytes (repr would elide large arrays; non-numeric values
        # hash via repr), sea states, and the iteration count
        h = hashlib.sha256()
        from .io_utils import clean_raft_dict
        h.update(repr(clean_raft_dict(base_design)).encode())
        h.update(repr([str(path) for path, _ in axes]).encode())
        for combo in combos:
            for v in combo:
                try:
                    h.update(np.asarray(v, dtype=float).tobytes())
                except (TypeError, ValueError):
                    h.update(repr(v).encode())
        for s in sea_states:
            h.update(np.asarray(s, dtype=float).tobytes())
        h.update(str(n_iter).encode())
        sig = h.hexdigest()
    if checkpoint and os.path.exists(checkpoint):
        with np.load(checkpoint, allow_pickle=False) as dat:
            if str(dat["sig"]) == sig and dat["motion_std"].shape == results.shape:
                results = np.array(dat["motion_std"])
                done = np.array(dat["done"])
                if display:
                    print(f"sweep resume: {int(done.sum())}/{n_designs} designs already done")

    batched = None

    for start in range(0, n_designs, chunk_size):
        stop = min(start + chunk_size, n_designs)
        if done[start:stop].all():
            continue

        params_list = []
        static = template = None
        for ic in range(start, stop):
            p, static, template = _compile_variant(base_design, axes, combos[ic], device)
            params_list.append(p)
            if display:
                print(f"compiled design {ic+1}/{n_designs}: {combos[ic]}")
        # pad a short final chunk by repeating the last design so every
        # chunk shares one leading shape (a second XLA compile would cost
        # more than the padded rows; padded results are discarded)
        n_real = len(params_list)
        if n_designs > chunk_size:
            params_list += [params_list[-1]] * (chunk_size - n_real)

        if batched is None:
            solve_p = make_parametric_solver(static, n_iter=n_iter)
            # vmap axes: designs (params), then cases (waves) — one executable
            batched = jax.jit(jax.vmap(jax.vmap(solve_p, in_axes=(None, 0, 0)),
                                       in_axes=(0, None, None)))
            w = jnp.asarray(template.w)
            zl, bl = [], []
            for ss in sea_states:
                Hs, Tp = ss[0], ss[1]
                beta = np.radians(ss[2]) if len(ss) > 2 else 0.0
                S = waves.jonswap(w, Hs, Tp)
                zl.append(jnp.sqrt(2.0 * S * template.dw) + 0j)
                bl.append(jnp.array([beta]))
            zetas = jnp.stack(zl)[:, None, :]
            betas = jnp.stack(bl)

        params_stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)
        Xi = batched(params_stacked, zetas, betas)  # [chunk, ncase, 1, 6, nw]
        results[start:stop] = np.asarray(
            jnp.sqrt(0.5 * jnp.sum(jnp.abs(Xi[:, :, 0]) ** 2, axis=-1)))[:n_real]
        done[start:stop] = True

        if checkpoint:
            tmp = f"{checkpoint}.{os.getpid()}.tmp.npz"  # .npz: savez keeps the name
            np.savez(tmp, sig=sig, motion_std=results, done=done)
            os.replace(tmp, checkpoint)

    return {"grid": grid, "motion_std": results}
