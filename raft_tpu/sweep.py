"""Design-sweep execution layer (parametersweep-equivalent, batched).

The reference sweeps designs with serial nested for-loops re-running
the full model per point (raft/parametersweep.py:56-100) — its prime
TPU-sharding target (SURVEY.md §2.3).  Here a sweep runs as:

1.  host loop compiling each design variant (geometry changes, same
    topology → identical trace shapes, so the jitted case solver is
    compiled ONCE and reused across all variants);
2.  per design, the sea-state batch solves as one vmapped, mesh-sharded
    device call (raft_tpu.parallel.CaseBatch);
3.  response statistics reduce on device.

``sweep`` mirrors the reference's mutate-design-dict pattern: you give
a base design, a list of (path, values) axes, and get the full factorial
grid of metrics.
"""

from __future__ import annotations

import copy
import itertools

import numpy as np
import jax
import jax.numpy as jnp

from .core.model import Model
from .ops import waves


def set_in_design(design, path, value):
    """Set a nested design-dict entry; path like
    'platform.members.0.d' or a callable(design, value)."""
    if callable(path):
        path(design, value)
        return
    keys = path.split(".")
    node = design
    for k in keys[:-1]:
        node = node[int(k)] if k.lstrip("-").isdigit() else node[k]
    last = keys[-1]
    if last.lstrip("-").isdigit():
        node[int(last)] = value
    else:
        node[last] = value


def sweep(base_design, axes, sea_states, n_iter=15, device=None, display=0):
    """Run a factorial design sweep.

    Parameters
    ----------
    base_design : dict
        RAFT design dict (strip-theory configuration).
    axes : list of (path_or_callable, values)
        Design-variable axes; full factorial product is evaluated.
    sea_states : list of (Hs, Tp) or (Hs, Tp, heading_deg)
        Wave cases solved (batched) for every design variant.

    Returns
    -------
    dict with 'grid' (the factorial list of value tuples) and
    'motion_std' [n_designs, n_cases, 6] motion standard deviations.
    """
    from .parallel.case_solve import design_params, make_parametric_solver

    combos = list(itertools.product(*[v for _, v in axes]))
    n_designs = len(combos)
    grid = []

    # host pass: compile every design variant into a params pytree
    # (identical topology -> identical shapes -> ONE jitted executable)
    params_list = []
    static = None
    template = None
    for ic, combo in enumerate(combos):
        design = copy.deepcopy(base_design)
        for (path, _), val in zip(axes, combo):
            set_in_design(design, path, val)
        grid.append(combo)

        model = Model(design)
        fowt = model.fowtList[0]
        fowt.setPosition(np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0]))
        fowt.calcStatics()
        fowt.calcHydroConstants()
        p, s = design_params(fowt, include_aero=False, device=device)
        params_list.append(p)
        static = s
        template = fowt
        if display:
            print(f"compiled design {ic+1}/{n_designs}: {combo}")

    params_stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)

    solve_p = make_parametric_solver(static, n_iter=n_iter)
    # vmap axes: designs (params), then cases (waves) — one executable
    batched = jax.jit(jax.vmap(jax.vmap(solve_p, in_axes=(None, 0, 0)),
                               in_axes=(0, None, None)))

    w = jnp.asarray(template.w)
    zetas, betas = [], []
    for ss in sea_states:
        Hs, Tp = ss[0], ss[1]
        beta = np.radians(ss[2]) if len(ss) > 2 else 0.0
        S = waves.jonswap(w, Hs, Tp)
        zetas.append(jnp.sqrt(2.0 * S * template.dw) + 0j)
        betas.append(jnp.array([beta]))
    zetas = jnp.stack(zetas)[:, None, :]
    betas = jnp.stack(betas)

    Xi = batched(params_stacked, zetas, betas)  # [ndesign, ncase, 1, 6, nw]
    std = jnp.sqrt(0.5 * jnp.sum(jnp.abs(Xi[:, :, 0]) ** 2, axis=-1))  # [nd, nc, 6]
    return {"grid": grid, "motion_std": np.asarray(std)}
