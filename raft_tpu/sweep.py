"""Design-sweep execution layer (parametersweep-equivalent, batched).

The reference sweeps designs with serial nested for-loops re-running
the full model per point (raft/parametersweep.py:56-100).  Here a sweep
runs end to end as array programs:

1.  host probe-parsing learns which geometry/mooring leaves each sweep
    axis touches and assembles the stacked [n_designs, ...] variant
    batch with numpy indexing (raft_tpu.parallel.design_batch) — host
    cost is O(n_axes x n_values), independent of the grid size;
2.  ONE jitted call per chunk compiles every variant's physics (member
    statics rollup, hydro constants, mooring stiffness) via a vmapped
    design compiler and solves the whole (design x sea-state) batch,
    with response statistics reduced on device;
3.  axes outside the batched compiler's scope (turbine, site, settings,
    topology changes) fall back to the per-variant model path.

``sweep`` mirrors the reference's mutate-design-dict pattern: you give
a base design, a list of (path, values) axes, and get the full factorial
grid of metrics.
"""

from __future__ import annotations

import copy
import functools
import itertools
import threading
import time
import warnings
import zipfile

import numpy as np
import jax
import jax.numpy as jnp

from . import profiling
from .analysis.contracts import shape_contract
from .config import (chaos_config, executor_config, flightrec_config,
                     health_config, resilience_config, resolve_mesh_devices)
from .core.model import Model
from .obs import ledger as obs_ledger
from .obs import log as obs_log
from .obs.trace import maybe_trace
from .ops import waves
from .parallel.design_batch import (SweepAxisError, check_batch_capability,
                                    pack_rows, pack_spec, set_in_design,
                                    stack_variants, unpack_leaves,
                                    variant_finite_mask)
from .parallel.compile_service import CompileService
from .parallel.executor import (CheckpointWriter, FaultIsolator,
                                chunk_selector, start_host_fetch,
                                wait_for_executables)
from .robust import (STATUS_NAN, STATUS_OK, STATUS_QUARANTINED, SolveHealth,
                     build_report, classify_health, format_report,
                     run_isolated)
from .robust import chaos as chaos_mod
from .robust import elastic
from .robust.health import (STATUS_NAMES, iterations_to_tolerance,
                            reduce_design_status)

__all__ = ["sweep", "precompile", "set_in_design", "case_aero_params"]

_LOG = obs_log.get_logger("sweep")

# Test seam for fault-injection: when set, called as
# ``hook(idx, dispatch)`` in place of the chunk dispatch (``idx`` is the
# padded design-index array, ``dispatch`` the real executor).  Lets the
# robustness tests make one chunk raise or one design emit NaN without
# building a pathological physics model (tests/test_robust.py).
_CHUNK_EXEC_HOOK = None

# In-process template memo: repeat sweeps of the SAME base design (new
# axis values / sea states / wind cases) reuse the template model, the
# batched design compiler, and the compiled chunk executables instead of
# re-jitting everything.  This is the FIRST level of the compile story
# (docs/performance.md): memo hit -> zero lowering/compile; memo miss ->
# the serialized-executable cache (RAFT_TPU_EXEC_CACHE) deserializes a
# prior process's executables; then the persistent XLA compile cache
# (config.enable_compilation_cache) turns a fresh compile into a
# deserialization; only a miss of all three pays real XLA compilation —
# on background workers, overlapped with the host-side plan phase
# (parallel/compile_service.py), ~27 s serialized at the BENCH_r05
# volume otherwise.  Keyed by design content, so a mutated design never
# hits a stale entry.
_TEMPLATE_MEMO: dict = {}
_TEMPLATE_MEMO_MAX = 4
# Concurrent sweep() entry (the serve layer, DOE drivers with worker
# threads) mutates the memo from several threads: entry creation +
# eviction and the nested stack/resident/bem/jitted sub-cache writes all
# happen under this lock.  Reads stay lock-free (dict.get is atomic
# under the GIL and entries are never mutated in place once published —
# sub-caches only grow).  Contract: concurrent WARM entry is
# compile-free and bit-identical to sequential; concurrent COLD entry
# on the same design may build the executables redundantly (last memo
# write wins, both results correct) — warm once, then fan out.
_MEMO_LOCK = threading.Lock()


def _design_hash(base_design):
    """Content hash of a design dict (single canonicalization shared by
    the checkpoint signature and the template memo, so the two can never
    disagree about design identity)."""
    import hashlib

    from .io_utils import clean_raft_dict

    return hashlib.sha256(repr(clean_raft_dict(base_design)).encode()).hexdigest()


def _template_key(base_design, n_iter, with_aero):
    return (_design_hash(base_design), int(n_iter), bool(with_aero))


def _design_case_mesh(devices, n_cases, shape=None):
    """Factor ``devices`` into the production (design, case) mesh.

    The default factorization puts EVERY device on the 'design' axis —
    the big axis in a DOE sweep — and keeps the case axis at 1.  That
    choice is what makes the mesh result bit-identical to the
    single-device run: each shard's local program then sees the full
    sea-state batch (``n_cases``) and the requested per-shard chunk of
    designs, i.e. exactly the shapes the 1x1 mesh compiles, and XLA:CPU
    codegen is batch-extent-sensitive in its last bits.  One device is
    the degenerate 1x1 mesh — the production sweep runs the SAME
    sharded code path at every scale.

    ``shape`` (from ``RAFT_TPU_MESH="DxC"``) pins the factorization
    instead; its case extent must then divide ``n_cases``.  A case
    extent > 1 shrinks each shard's local sea-state batch, so results
    agree with single-device to floating-point tolerance (~1 ulp)
    rather than bitwise — useful when designs are scarce and sea
    states plentiful, opt-in by construction.
    """
    from jax.sharding import Mesh

    n_dev = len(devices)
    if shape is not None:
        n_design_ax, n_case_ax = shape
        if n_design_ax * n_case_ax != n_dev:
            raise ValueError(
                f"mesh shape {n_design_ax}x{n_case_ax} does not use the "
                f"{n_dev} selected device(s)")
        if n_cases % n_case_ax:
            raise ValueError(
                f"mesh case axis {n_case_ax} does not divide the "
                f"{n_cases} sea state(s); pick a case extent that does")
    else:
        n_design_ax, n_case_ax = n_dev, 1
    return Mesh(np.asarray(devices).reshape(n_design_ax, n_case_ax),
                ("design", "case"))


def _turbine_variant_fowt(fowt, base_design, axes, aero_axes, combo):
    """Light turbine-variant view of the template FOWT.

    Aero axes change ONLY the turbine dict (stack_variants proved the
    geometry/mooring leaves are untouched), so a full ``Model`` rebuild
    per variant (~1.7 s host each — O(#combos) for a control-gain DOE)
    is wasted work: shallow-copy the template FOWT and rebuild just its
    rotors from the mutated turbine dict, replicating the FOWT
    constructor's turbine preprocessing (core/fowt.py:286-296).
    ``calcTurbineConstants`` then writes its A_aero/B_aero onto the
    copy without touching the template.
    """
    from .core.fowt import prepare_turbine_dict
    from .rotor.rotor import Rotor

    d = copy.deepcopy(base_design)
    for ia in aero_axes:
        set_in_design(d, axes[ia][0], combo[ia])
    turbine = d["turbine"]

    fv = copy.copy(fowt)
    fv.nrotors = prepare_turbine_dict(turbine, d.get("site", {}))
    fv.rotorList = [Rotor(turbine, fowt.w, ir) for ir in range(fv.nrotors)]
    fv.r6 = np.array([fv.x_ref, fv.y_ref, 0, 0, 0, 0], dtype=float)
    for rot in fv.rotorList:
        rot.setPosition(r6=fv.r6)
    return fv


def _compile_variant(base_design, axes, combo, device):
    """Per-variant model path (fallback): build the full Model and
    extract solver params eagerly."""
    from .parallel.case_solve import design_params

    design = copy.deepcopy(base_design)
    for (path, _), val in zip(axes, combo):
        set_in_design(design, path, val)
    model = Model(design)
    fowt = model.fowtList[0]
    fowt.setPosition(np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0]))
    fowt.calcStatics()
    fowt.calcHydroConstants()
    p, s = design_params(fowt, include_aero=False, device=device)
    return p, s, fowt


def case_aero_params(fowt, wind_cases):
    """Aero-servo impedance contributions per case, stacked.

    Runs ``calcTurbineConstants`` on the template FOWT for each case dict
    (wind_speed/turbulence/...; raft_fowt.py:773-845) and returns
    ``{"A": [n_case, nw, 6, 6], "B": [n_case, nw, 6, 6]}`` — the terms a
    platform-geometry sweep can factor out of the design axis because
    the rotor/tower are unchanged across variants.
    """
    A_list, B_list = [], []
    for case in wind_cases:
        fowt.calcTurbineConstants(case, ptfm_pitch=0)
        A_list.append(np.moveaxis(np.sum(fowt.A_aero, axis=3), 2, 0))
        B_list.append(np.moveaxis(np.sum(fowt.B_aero, axis=3), 2, 0)
                      + np.sum(fowt.B_gyro, axis=2)[None, :, :])
    return {"A": jnp.asarray(np.stack(A_list)), "B": jnp.asarray(np.stack(B_list))}


def _sea_state_waves(template, sea_states):
    # zetas stay real here: the parametric solver casts to complex inside
    # jit (the TPU plugin cannot transfer complex arrays eagerly)
    w = jnp.asarray(template.w)
    zl, bl = [], []
    for ss in sea_states:
        Hs, Tp = ss[0], ss[1]
        beta = np.radians(ss[2]) if len(ss) > 2 else 0.0
        S = waves.jonswap(w, Hs, Tp)
        zl.append(jnp.sqrt(2.0 * S * template.dw))
        bl.append(jnp.array([beta]))
    return jnp.stack(zl)[:, None, :], jnp.stack(bl)


def _sweep_signature(base_design, axes, combos, sea_states, n_iter, wind):
    """Checkpoint identity: base design, axis PATHS (a callable axis repr
    includes a per-process address, so such sweeps conservatively never
    resume), exact value bytes (repr would elide large arrays;
    non-numeric values hash via repr), sea states, wind cases, and the
    iteration count."""
    import hashlib

    from .io_utils import clean_raft_dict

    h = hashlib.sha256()
    h.update(_design_hash(base_design).encode())
    h.update(repr([str(path) for path, _ in axes]).encode())
    for combo in combos:
        for v in combo:
            try:
                h.update(np.asarray(v, dtype=float).tobytes())
            except (TypeError, ValueError):
                h.update(repr(v).encode())
    for s in sea_states:
        h.update(np.asarray(s, dtype=float).tobytes())
    h.update(str(n_iter).encode())
    h.update(repr(wind).encode())
    return h.hexdigest()


def sweep(base_design, axes, sea_states, n_iter=15, device=None, display=0,
          checkpoint=None, chunk_size=256, wind=None, devices=None,
          health=None, flightrec=None, chaos=None, grid=None):
    """Run a factorial design sweep.

    Parameters
    ----------
    base_design : dict
        RAFT design dict (strip-theory configuration).
    axes : list of (path_or_callable, values)
        Design-variable axes; full factorial product is evaluated.
    grid : list of value tuples, optional
        Explicit design points — one value per axis in ``axes`` — run
        INSTEAD of the factorial product.  This is the coalescing entry
        point for :mod:`raft_tpu.serve`: many small requests concatenate
        their points into one grid so they share the same fixed-shape
        padded chunks, and results come back in grid order (row ``i`` of
        every result array is ``grid[i]``).  The executables, template
        memo, stack memo, and checkpoint signature all key off the
        actual point list, so a grid sweep is bit-identical to the same
        points run factorially (row independence: chunk programs are
        vmapped with padding rows, so cohabiting points never interact).
    sea_states : list of (Hs, Tp) or (Hs, Tp, heading_deg)
        Wave cases solved (batched) for every design variant.
    devices : sequence of jax devices, optional
        Pod-scale execution: the chunk's stacked design leaves are
        sharded over the 'design' axis and the sea-state batch over the
        'case' axis of a 2-D device mesh (the north-star sharding:
        "parametersweep shards design variants over the pod",
        BASELINE.json; reference loop raft/parametersweep.py:56-100).
        The sweep ALWAYS runs this mesh path: ``None`` consults
        ``RAFT_TPU_MESH`` (:func:`raft_tpu.config.resolve_mesh_devices`)
        and otherwise falls back to the single device picked by
        ``device`` — the degenerate 1x1 mesh of the same code, not a
        separate branch; results are bit-identical at every mesh shape.
    wind : list of case dicts, optional
        One reference-style case dict per sea state (wind_speed,
        turbulence, ...).  Turns the aero-servo impedance ON: the rotor
        contributions are computed once on the base design (the rotor is
        unchanged by platform-geometry axes) and folded into each case's
        solve (raft_model.py:905-914).  Scope note: the responses are
        WAVE-excitation-only with the aero-servo impedance (A_aero,
        B_aero + gyro) folded in at ptfm_pitch=0 — the wind-excitation
        forcing spectrum (f_aero) is not added to motion_std/AxRNA_std.
        This matches the reference's own behaviour: raft_model.py:895
        zeroes f_aero before the solve and the rotor-excitation block
        (raft_model.py:1086-1095) is commented out, so the reference
        sweep's turbulent-wind excitation is equally disabled — wind
        enters through the impedance (and mean loads) only, and this
        sweep faithfully mirrors that.
    checkpoint : str, optional
        Path to an .npz progress file.  Designs execute in chunks of
        ``chunk_size``; after each chunk the partial results are saved
        (atomically), and a re-run of the same sweep resumes from the
        first unfinished chunk — the sweep-level resumability SURVEY.md
        §5 calls for (the reference's serial sweep restarts from scratch).
        A checkpoint from a *different* sweep signature is ignored, and a
        corrupt/unreadable checkpoint file is warned about and treated as
        absent (the sweep starts fresh) instead of raising.  Checkpoints
        written by older versions (no ``status`` array) resume with the
        already-done designs marked ok.
    health : bool or dict, optional
        Solve-health telemetry configuration
        (:data:`raft_tpu.config.SOLVE_HEALTH_DEFAULTS`): ``False``
        disables the in-graph health channel (the seed solver's exact
        trace), ``True``/``None`` uses the defaults + environment
        overrides, a dict overrides individual keys.  ``resid_tol`` /
        ``cond_tol`` classify on the host and never recompile anything;
        ``tik_eps`` / ``tik_cond_tol`` are constants of the solver trace.
        See docs/robustness.md.
    flightrec : bool or dict, optional
        Flight-recorder configuration
        (:data:`raft_tpu.config.FLIGHTREC_DEFAULTS`): ``None`` reads the
        ``RAFT_TPU_FLIGHTREC*`` environment (off when unset), ``True``
        turns on the in-graph per-iteration Borgman residual trace
        (requires the health channel; adds a ``'convergence'`` entry to
        the results and ``convergence_summary`` ledger events),
        ``False`` forces everything off, a dict overrides individual
        keys.  With a capture ``dir`` armed, quarantined designs (and
        status transitions at/above the configured ``severity``) write
        self-contained replay bundles — see
        :mod:`raft_tpu.obs.flightrec` and docs/robustness.md.  Off (the
        default) is the seed trace: bit-identical results, zero
        additional XLA compiles.
    chaos : bool or str or dict, optional
        Deterministic fault injection
        (:mod:`raft_tpu.robust.chaos`): ``None`` reads ``RAFT_TPU_CHAOS``
        (disarmed when unset), a string is a spec override (e.g.
        ``"poison_fetch:chunk=1"``), ``False`` force-disables, a dict
        overrides :func:`raft_tpu.config.chaos_config` keys.  Disarmed
        (the production default) the harness costs nothing: results and
        compile counts are bit-identical to a build without it.  See
        docs/robustness.md "Chaos testing & elasticity".

    Resilience: the watchdog / graceful-shutdown / re-mesh knobs
    (:func:`raft_tpu.config.resilience_config`) are environment-driven —
    ``RAFT_TPU_WATCHDOG`` arms per-chunk dispatch->fetch deadlines that
    route a hung chunk into quarantine, SIGTERM (by default) drains
    in-flight chunks and raises
    :class:`~raft_tpu.robust.elastic.SweepPreempted` with a resumable
    checkpoint flushed, and a device loss mid-sweep re-meshes onto the
    surviving devices and resumes in place.  All of it is host-side
    scheduling: no knob changes a traced program.

    Returns
    -------
    dict with 'grid' (the factorial list of value tuples),
    'motion_std' [n_designs, n_cases, 6] motion standard deviations,
    'AxRNA_std' [n_designs, n_cases] nacelle fore-aft acceleration
    standard deviations (batched path; the saveTurbineOutputs channel
    the WEIS Max_Nacelle_Acc aggregate reads), and per-design
    properties 'mass' [kg], 'displacement'
    (displaced mass rho*V [kg], getOutputs convention), 'GMT' [m]
    [n_designs] (the quantities the reference sweep's getOutputs
    collects; NaN on the per-variant fallback path).  Also attached:
    'status' int8 [n_designs] per-design health codes (0 ok,
    1 non-converged, 2 ill-conditioned, 3 nan, 4 quarantined — worst
    over cases; see raft_tpu.robust.health), 'health' (per-design worst
    Borgman residual and pivot-conditioning ratio), and 'report' (the
    structured end-of-sweep summary, printed when ``display``).  Feed
    the result to :func:`raft_tpu.sweep_post.plot_sweep_contours` for
    the reference-style contour figures (parametersweep.py:119-561).

    Observability: with ``RAFT_TPU_LEDGER=dir`` set, the whole call is
    bracketed in a run-ledger run (:mod:`raft_tpu.obs`) — a JSON-lines
    event file records the template/compile cache story, per-chunk
    dispatch/fetch/commit with pipeline depth, transfer bytes,
    quarantine activity, and per-phase timings, keyed by a run id and a
    design-batch fingerprint.  With ``RAFT_TPU_METRICS``/
    ``RAFT_TPU_METRICS_PORT``, the same events also feed the live
    metrics registry (:mod:`raft_tpu.obs.metrics`) and its ``/metrics``
    + ``/status`` endpoint.  Both unset (the default) takes the
    zero-instrumentation path: no events, no listeners, bit-identical
    results and zero additional XLA compiles.
    """
    devices, mesh_shape = resolve_mesh_devices(devices, device)
    run = obs_ledger.NULL_RUN
    if obs_ledger.observing():
        if grid is not None:
            n_designs = len(grid)
        else:
            n_designs = 1
            for _, v in axes:
                n_designs *= len(v)
        run = obs_ledger.start_run(
            "sweep",
            fingerprint={"design": _design_hash(base_design)[:16],
                         "axes": [str(p) for p, _ in axes],
                         "n_designs": n_designs,
                         "n_cases": len(sea_states)},
            meta={"n_iter": int(n_iter), "chunk_size": int(chunk_size),
                  "wind": wind is not None,
                  "n_devices": len(devices)})
    try:
        state = None
        while True:
            try:
                out = _sweep_impl(base_design, axes, sea_states,
                                  n_iter=n_iter, device=device,
                                  display=display, checkpoint=checkpoint,
                                  chunk_size=chunk_size, wind=wind,
                                  devices=devices, mesh_shape=mesh_shape,
                                  health=health, flightrec=flightrec,
                                  run=run, chaos=chaos, grid=grid,
                                  _resume_state=state)
                break
            except elastic.RemeshRequired as rq:
                survivors = elastic.surviving_devices(rq.devices, rq.error)
                if not survivors:
                    raise rq.error
                run.emit("device_lost",
                         error=f"{type(rq.error).__name__}: {rq.error}",
                         devices=[int(d.id) for d in rq.devices])
                run.emit("remesh",
                         from_devices=[int(d.id) for d in rq.devices],
                         to_devices=[int(d.id) for d in survivors])
                obs_log.warn(
                    _LOG,
                    f"sweep: device loss mid-sweep "
                    f"({type(rq.error).__name__}: {rq.error}); re-meshing "
                    f"onto {len(survivors)} surviving device(s) and "
                    f"resuming", RuntimeWarning)
                # the interrupted attempt's in-memory arrays are fresher
                # than any checkpoint; re-enter with them and a mesh
                # rebuilt from the survivors (executables re-key through
                # the placement-aware jit_key / exec-cache tag)
                devices, mesh_shape = survivors, None
                state = rq.state
        run.finish(ok=True, counts=out["report"]["counts"])
        return out
    except elastic.SweepPreempted as e:
        run.finish(ok=False, reason="preempted",
                   error=f"{type(e).__name__}: {e}")
        raise
    except BaseException as e:
        run.finish(ok=False, error=f"{type(e).__name__}: {e}")
        raise
    finally:
        run.close()


def precompile(base_design, axes, sea_states, n_iter=15, device=None,
               display=0, chunk_size=256, wind=None, devices=None,
               health=None, flightrec=None, grid=None):
    """Warm up the sweep executables without dispatching any chunk.

    Runs :func:`sweep`'s plan phase exactly — template model, variant
    stacking, split-program lowering, background compile (through the
    compile service and, when ``RAFT_TPU_EXEC_CACHE`` is set, the
    serialized-executable cache) — then returns once the chunk
    executables are built and memoized.  Afterwards:

    * a ``sweep()`` in THIS process with the same design/axes shape
      signature reuses the executables straight from the in-process
      template memo (zero lowering, zero XLA), and
    * with ``RAFT_TPU_EXEC_CACHE`` pointed at a shared directory, ANY
      fresh process deserializes them instead of compiling — the
      pre-bake hook for serving workers, autoscaled replicas, and CI.

    Accepts the same arguments as :func:`sweep` (minus ``checkpoint`` —
    nothing is executed, so there is no progress to persist).  The
    factorial size of ``axes`` does not matter for the executables
    beyond the chunk extent: precompiling with a small representative
    grid warms sweeps over any same-shaped axes.

    Returns a report dict: ``mode`` (``'fallback'`` means the axes fall
    outside the batched path and there is nothing to AOT-precompile),
    ``compiled`` mapping executable key to its build ``source``
    (``'compile'`` | ``'exec_cache'``) and ``seconds``, and ``cache``
    (``'memo'`` when the executables were already memoized in-process).
    """
    devices, mesh_shape = resolve_mesh_devices(devices, device)
    run = obs_ledger.NULL_RUN
    if obs_ledger.observing():
        if grid is not None:
            n_designs = len(grid)
        else:
            n_designs = 1
            for _, v in axes:
                n_designs *= len(v)
        run = obs_ledger.start_run(
            "precompile",
            fingerprint={"design": _design_hash(base_design)[:16],
                         "axes": [str(p) for p, _ in axes],
                         "n_designs": n_designs,
                         "n_cases": len(sea_states)},
            meta={"n_iter": int(n_iter), "chunk_size": int(chunk_size),
                  "wind": wind is not None,
                  "n_devices": len(devices)})
    try:
        out = _sweep_impl(base_design, axes, sea_states, n_iter=n_iter,
                          device=device, display=display, checkpoint=None,
                          chunk_size=chunk_size, wind=wind, devices=devices,
                          mesh_shape=mesh_shape, health=health,
                          flightrec=flightrec, run=run, grid=grid,
                          compile_only=True)
        run.finish(ok=True)
        return out
    except BaseException as e:
        run.finish(ok=False, error=f"{type(e).__name__}: {e}")
        raise
    finally:
        run.close()


def _sweep_impl(base_design, axes, sea_states, *, n_iter, device, display,
                checkpoint, chunk_size, wind, devices, health, run,
                flightrec=None, mesh_shape=None, compile_only=False,
                chaos=None, grid=None, _resume_state=None):
    """:func:`sweep` body; ``run`` is the active ledger run (NULL_RUN
    when telemetry is off — every ``run.emit`` is then a no-op and all
    byte/stat collection is gated behind ``run.enabled``).

    ``compile_only`` (:func:`precompile`) stops after the chunk
    executables are built and memoized — no chunk is dispatched, no
    results are produced; returns a small build report instead."""
    import os

    from .parallel.case_solve import make_parametric_solver
    from .parallel.design_batch import _vkey, make_batch_compiler, rna_params_for

    if grid is not None:
        # explicit design points (the serve-layer coalescing path):
        # every tuple supplies one value per axis, evaluated in grid
        # order instead of the factorial product
        combos = [tuple(pt) for pt in grid]
        if not combos:
            raise ValueError("grid must contain at least one design point")
        n_ax = len(axes)
        for pt in combos:
            if len(pt) != n_ax:
                raise ValueError(
                    f"grid point has {len(pt)} values for {n_ax} axes: "
                    f"{pt!r}")
    else:
        combos = list(itertools.product(*[v for _, v in axes]))
    n_designs = len(combos)
    n_cases = len(sea_states)
    if wind is not None and len(wind) != n_cases:
        raise ValueError("wind must align with sea_states (one case dict each)")

    if health is False:
        hcfg = health_config({"enabled": False})
    elif health is None or health is True:
        hcfg = health_config()
    else:
        hcfg = health_config(dict(health))
    run_health = bool(hcfg["enabled"])

    if flightrec is False:
        fcfg = flightrec_config({"enabled": False})
    elif flightrec is None:
        fcfg = flightrec_config()
    elif flightrec is True:
        fcfg = flightrec_config({"enabled": True})
    else:
        fcfg = flightrec_config(dict(flightrec))
    # the residual trace rides the health scan's carry as ys — no health
    # channel, no trace (case_solve enforces the same invariant)
    run_trace = bool(fcfg["enabled"] and fcfg["convergence"] and run_health)
    # per-iteration Borgman residual trajectories, filled per chunk like
    # the result arrays (NaN = never computed / fallback path row)
    conv_trace = (np.full((n_designs, n_cases, int(n_iter)), np.nan)
                  if run_trace else None)

    # resilience knobs + chaos plan (raft_tpu.robust.elastic / .chaos).
    # Both disarmed (the default) costs nothing on the sweep path: no
    # traced program sees any of this, so results and compile counts
    # stay bit-identical.  On re-mesh re-entry the plan is carried in
    # ``_resume_state`` so chaos fire budgets persist across attempts.
    rcfg = resilience_config()
    if _resume_state is not None:
        chaos_plan = _resume_state.get("chaos_plan")
        if chaos_plan is not None:
            chaos_plan.set_run(run)
    else:
        chaos_plan = None
        if chaos is not False and (chaos is not None
                                   or chaos_config()["spec"]):
            chaos_plan = chaos_mod.plan_for(
                _design_hash(base_design)[:16], run=run, chaos=chaos)

    # the production path is ALWAYS the (design, case) mesh — a single
    # device is the degenerate 1x1 mesh of the same sharded code, not a
    # separate branch (callers resolve the device set via
    # config.resolve_mesh_devices; RAFT_TPU_MESH scales it out)
    devices = list(devices if devices is not None
                   else resolve_mesh_devices(None, device)[0])
    # the per-shard design extent IS the single-device chunk extent:
    # every shard's local program compiles exactly the shapes the 1x1
    # mesh compiles (the bit-identity contract).  Fixed before the mesh
    # is built so the design axis can be sized to the workload: shards
    # beyond ceil(n_designs / chunk_local) would only ever hold padding
    # rows, so they are dropped rather than silently burning memory
    chunk_local = max(1, min(int(chunk_size), n_designs))
    if mesh_shape is None and len(devices) > 1:
        n_useful = -(-n_designs // chunk_local)
        if n_useful < len(devices):
            if display:
                obs_log.display(
                    _LOG,
                    f"sweep: mesh design axis sized to workload — using "
                    f"{n_useful} of {len(devices)} device(s) "
                    f"({n_designs} designs / chunk {chunk_local})")
            devices = devices[:n_useful]
    mesh = _design_case_mesh(devices, n_cases, shape=mesh_shape)
    n_design_ax = mesh.devices.shape[0]
    mesh_sig = (mesh.devices.shape, tuple(str(d) for d in devices))
    if device is None:
        device = devices[0]  # per-variant fallback path placement

    def _fresh_state():
        return (np.full((n_designs, n_cases, 6), np.nan),
                np.full((n_designs, n_cases), np.nan),
                {k: np.full(n_designs, np.nan)
                 for k in ("mass", "displacement", "GMT")},
                np.zeros(n_designs, dtype=bool),
                np.zeros(n_designs, dtype=np.int8),
                np.full(n_designs, np.nan),
                np.full(n_designs, np.nan))

    # status: per-design int8 health codes (raft_tpu.robust.health).
    # `done` keeps its resume semantics — "this design needs no more
    # work" — which now covers both computed AND given-up (quarantined)
    # designs; `status` is what distinguishes them.
    (results, nacelle_acc, props, done,
     status, health_resid, health_cond) = _fresh_state()
    sig = None
    if checkpoint:
        sig = _sweep_signature(base_design, axes, combos, sea_states, n_iter, wind)
        _clean_stale_tmp(checkpoint)
        if os.path.exists(checkpoint):
            # a half-written/corrupt checkpoint (killed mid-save, disk
            # full, truncated copy) must not be able to kill the sweep it
            # exists to protect: unreadable -> warn and start fresh
            try:
                with np.load(checkpoint, allow_pickle=False) as dat:
                    if (str(dat["sig"]) == sig and dat["motion_std"].shape == results.shape
                            and "AxRNA_std" in dat and all(k in dat for k in props)):
                        results = np.array(dat["motion_std"])
                        nacelle_acc = np.array(dat["AxRNA_std"])
                        done = np.array(dat["done"])
                        for k in props:
                            props[k] = np.array(dat[k])
                        # old-schema checkpoints (pre-status) resume with
                        # already-done designs treated as ok (zeros)
                        if "status" in dat and dat["status"].shape == status.shape:
                            status = np.array(dat["status"], dtype=np.int8)
                        if "health_resid" in dat and dat["health_resid"].shape == health_resid.shape:
                            health_resid = np.array(dat["health_resid"])
                        if "health_cond" in dat and dat["health_cond"].shape == health_cond.shape:
                            health_cond = np.array(dat["health_cond"])
                        if display:
                            obs_log.display(_LOG, f"sweep resume: {int(done.sum())}/{n_designs} designs already done")
            except (zipfile.BadZipFile, ValueError, KeyError, OSError, EOFError) as e:
                obs_log.warn(
                    _LOG,
                    f"sweep: checkpoint {checkpoint!r} unreadable "
                    f"({type(e).__name__}: {e}); starting fresh",
                    RuntimeWarning)
                (results, nacelle_acc, props, done,
                 status, health_resid, health_cond) = _fresh_state()

    if _resume_state is not None:
        # elastic re-mesh re-entry: the interrupted attempt's in-memory
        # arrays are at least as fresh as any checkpoint on disk (the
        # writer coalesces), so they win over the load above
        results = _resume_state["results"]
        nacelle_acc = _resume_state["nacelle_acc"]
        props = _resume_state["props"]
        done = _resume_state["done"]
        status = _resume_state["status"]
        health_resid = _resume_state["health_resid"]
        health_cond = _resume_state["health_cond"]
        if run_trace and _resume_state.get("conv_trace") is not None:
            conv_trace = _resume_state["conv_trace"]
        if display:
            obs_log.display(
                _LOG, f"sweep re-mesh resume: {int(done.sum())}/"
                      f"{n_designs} designs already done")

    def _finalize():
        out = {"grid": combos, "motion_std": results,
               "AxRNA_std": nacelle_acc, **props,
               "status": status,
               "health": {"resid": health_resid, "cond": health_cond}}
        if conv_trace is not None:
            out["convergence"] = {
                "resid_trace": conv_trace,
                "iters_to_tol": iterations_to_tolerance(
                    conv_trace, hcfg["resid_tol"]),
            }
        out["report"] = build_report(status, combos=combos, axes=axes,
                                     health=out["health"])
        if display:
            obs_log.display(_LOG, format_report(out["report"]))
        return out

    if done.all():
        return _finalize()

    # template model: frequency grid, rotors, mooring topology, fallback base.
    # Only the rotors need positioning (RNA constants + aero); the member
    # poses and mooring stiffness are traced inside the batch compiler, so
    # a full setPosition here would just pay their jit compiles twice.
    memo_key = _template_key(base_design, n_iter, wind is not None)
    memo = _TEMPLATE_MEMO.get(memo_key)
    run.emit("template_build", cache="hit" if memo is not None else "miss")
    if memo is not None:
        model, fowt = memo["model"], memo["fowt"]
    else:
        template_design = copy.deepcopy(base_design)
        model = Model(template_design)
        fowt = model.fowtList[0]
        fowt.r6 = np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0], dtype=float)
        for rot in fowt.rotorList:
            rot.setPosition(r6=fowt.r6)

    # ----- batched path: stacked geometry through one traced compiler -----
    stacked = None
    aero_axes = []
    try:
        if memo is not None:
            # the capability verdict depends on RAFT_TPU_BEM *now*, not
            # when the memoized compiler was built — re-check so a knob
            # flip between sweeps routes to the fallback (with its
            # capability_fallback event) instead of silently reusing a
            # compiler whose physics assumptions no longer hold
            check_batch_capability(fowt)
            compile_one, static = memo["compile_one"], memo["static"]
        else:
            compile_one, static = make_batch_compiler(fowt)
        template_leaves = (
            [jax.tree_util.tree_map(np.asarray, cm.geom) for cm in fowt.memberList],
            jax.tree_util.tree_map(np.asarray, fowt.ms.params) if fowt.ms is not None else None,
        )
        # memo the probe-parse/stacked batch too: a repeat sweep over the
        # SAME axes (e.g. a DOE driver polling, or the bench's repeat
        # measurement) re-derives an identical [n_designs, ...] batch —
        # ~1.4 s of host deepcopy/parse per call for the 1000-design grid.
        # (Axis paths + exact value bytes identify the batch; the design
        # itself is already pinned by memo_key.)  CALLABLE axis paths
        # carry only address identity — a recycled address would alias a
        # different mutation — so such sweeps never use the stack memo
        # (same conservative stance as the checkpoint signature).
        stack_key = None
        if not any(callable(p) for p, _ in axes):
            import hashlib

            h = hashlib.sha256(repr([str(p) for p, _ in axes]).encode())
            for combo in combos:
                for v in combo:
                    # full value identity (shape + dtype + bytes for
                    # arrays, repr otherwise) — byte-identical values of
                    # different shape/dtype must not collide
                    h.update(repr(_vkey(v)).encode())
            stack_key = h.hexdigest()
        cached_stack = (memo or {}).get("stacks", {}).get(stack_key) \
            if stack_key is not None else None
        if cached_stack is not None:
            stacked, treedef, aero_axes = cached_stack
        else:
            with profiling.phase("sweep/stack"):
                stacked, treedef, aero_axes = stack_variants(
                    base_design, axes, combos, rho=fowt.rho_water, g=fowt.g,
                    x_ref=fowt.x_ref, y_ref=fowt.y_ref,
                    heading_adjust=fowt.heading_adjust,
                    reference_leaves=template_leaves, display=display,
                )
    except SweepAxisError as e:
        if wind is not None:
            # the fallback exists for axes the batched compiler cannot
            # express (site/settings/topology changes) — exactly the axes
            # that would invalidate aero computed once on the base design
            raise ValueError(
                "wind-enabled sweeps need the batched design path; this "
                f"axis set falls outside it ({e}). Sweep site/topology axes "
                "without `wind`, or via the full Model per point.") from e
        # the fallback is a capability DOWNGRADE, not just a slow path:
        # its per-variant solve never runs calcBEM (core/fowt.py:353 —
        # A_BEM/B_BEM stay zero) and has no F_BEM/QTF term.  First-order
        # potential flow normally never gets here anymore — the batched
        # BEM tier (hydro/bem_batch.py) solves potMod members /
        # potModMaster 2-3 natively on the batched path — so landing in
        # this handler with a potential-flow design means the tier was
        # unavailable (RAFT_TPU_BEM=off, potFirstOrder file coefficients,
        # potSecOrder) or a non-batchable axis forced the downgrade.
        # Record the degradation in the ledger (capability_fallback ->
        # raft_capability_fallbacks_total) and, when forces are actually
        # being dropped, warn loudly (-> raft_warnings_total) instead of
        # proceeding silently.
        dropped = []
        if any(cm.topo.pot_mod for cm in fowt.memberList) \
                or fowt.potModMaster in (2, 3) \
                or getattr(fowt, "potFirstOrder", 0):
            dropped.append("BEM added mass/damping (A_BEM/B_BEM)")
        if getattr(fowt, "potSecOrder", 0):
            dropped.append("second-order wave forces (QTF)")
        run.emit("capability_fallback", reason="sweep_axis",
                 detail=str(e), dropped=dropped)
        if dropped:
            obs_log.warn(
                _LOG,
                "sweep: per-variant fallback path DROPS "
                + " and ".join(dropped)
                + f" for this potential-flow design ({e}); results omit "
                "those contributions — keep the sweep on the batched "
                "path (RAFT_TPU_BEM=auto solves first-order BEM "
                "natively there) or use the full Model.analyzeCases "
                "path for configurations the tier cannot express "
                "(potFirstOrder/potSecOrder)",
                RuntimeWarning, stacklevel=3)
        if display:
            obs_log.display(_LOG, f"sweep: falling back to per-variant model path ({e})")

    if stacked is not None:
        run.emit("stack_build",
                 cache="hit" if cached_stack is not None else "miss")
        spec = pack_spec(stacked)
        n_leaves = len(stacked)
        zetas, betas = _sea_state_waves(fowt, sea_states)

        # ---- batched potential-flow BEM tier (hydro/bem_batch.py) ----
        # Potential-flow configurations reach this batched path only when
        # the tier is available (parallel/design_batch.py raises
        # SweepAxisError otherwise), so `bem_active` here means "the
        # precompute below MUST run and its A/B/X leaves ride into every
        # chunk's params".  The solved heading set is the union of the
        # case headings, so the per-case interpolation in case_solve is
        # exact for every case.  Headings are expected in [0, 360):
        # within that range radians(h % 360) == radians(h) bit-exactly,
        # so bem_h entries equal the case betas and the interpolation
        # degenerates to a gather.
        from .config import bem_mode
        bem_active = (bem_mode() != "off"
                      and (any(cm.topo.pot_mod for cm in fowt.memberList)
                           or fowt.potModMaster in (2, 3)))
        bem_heads = None
        if bem_active:
            bem_heads = tuple(sorted({
                float(ss[2]) % 360.0 if len(ss) > 2 else 0.0
                for ss in sea_states}))

        # turbine (aero) axes: designs gather their turbine variant from
        # per-variant tables (RNA mass properties, aero-servo impedance,
        # hub heights) — the factorization the OMDAO DOE surface needs
        # (omdao_raft.py:480-696 varies control gains / rotor properties
        # per point).  Grouping designs into DISTINCT turbine-value
        # combinations is cheap and done up front so the warmup compile
        # below knows the variant-table shapes; the expensive per-variant
        # model builds happen after the compile has been kicked off.
        av_combos = []
        aero_idx = None
        if aero_axes:
            av_map: dict = {}
            aero_idx = np.zeros(n_designs, dtype=np.int32)
            for ic, c in enumerate(combos):
                key = tuple(_vkey(c[ia]) for ia in aero_axes)
                if key not in av_map:
                    av_map[key] = len(av_combos)
                    av_combos.append(c)
                aero_idx[ic] = av_map[key]
            if display:
                obs_log.display(
                    _LOG,
                    f"sweep: {len(av_combos)} turbine variants along aero axes "
                    f"{[str(axes[ia][0]) for ia in aero_axes]}")

        mode = ("sel_wind" if aero_axes and wind is not None
                else "sel" if aero_axes
                else "aero" if wind is not None else "plain")
        # a GLOBAL chunk is n_design_ax consecutive single-device-shaped
        # chunks, one per shard: each shard's local program compiles the
        # exact shapes of the 1x1 mesh — the property the bit-identity
        # contract rests on (XLA codegen differs in the last bits
        # between batch extents) — and the chunk phase scales by
        # dispatching n_design_ax single-device chunks per step.  On
        # the 1x1 mesh this is a no-op (chunk_local computed above).
        chunk_size = chunk_local * n_design_ax
        # the chunk executables are AOT-compiled against exact argument
        # shapes and shardings, so the memo keys them by everything that
        # shapes the programs: mode, the mesh placement (a Compiled
        # object is pinned to it — unlike jit it cannot transparently
        # recompile for a different device set), chunk/case/variant
        # extents — and checks treedef+spec (the packed transfer layout)
        place_sig = mesh_sig
        # the health channel changes the traced programs (extra outputs,
        # residual-carrying scan, Tikhonov constants), so it is part of
        # the executable identity
        health_sig = ((True, hcfg["tik_eps"], hcfg["tik_cond_tol"])
                      if run_health else (False,))
        if run_trace:
            # the residual trace adds a scan output to the traced
            # programs.  Extending the signature ONLY when tracing keeps
            # every trace-off memo/exec-cache key byte-identical to the
            # seed's — the zero-extra-compiles contract.
            health_sig = health_sig + (True,)
        if bem_active:
            # the BEM leaves extend partB's params signature (shapes
            # depend on the solved heading count); extending the key ONLY
            # when the tier is active keeps every BEM-off memo/exec-cache
            # key byte-identical to the seed's.
            health_sig = health_sig + (("bem", len(bem_heads)),)
        jit_key = (mode, place_sig, chunk_size, n_cases, len(av_combos),
                   health_sig)
        ecfg = executor_config()
        pipeline_depth = max(1, int(ecfg["pipeline_depth"]))
        run.emit("plan", mode=mode, n_chunks=-(-n_designs // chunk_size),
                 chunk_size=chunk_size, pipeline_depth=pipeline_depth,
                 resident=bool(ecfg["resident"]),
                 mesh=[int(s) for s in mesh.devices.shape],
                 devices=[int(d.id) for d in devices])
        if (memo is not None and memo["treedef"] == treedef
                and memo.get("spec") == spec):
            jitted = memo["jitted"].get(jit_key)
        else:
            jitted = None
        if jitted is not None:
            # repeat sweep: both chunk executables come straight from the
            # in-process template memo — no lowering, no XLA
            run.emit("compile_cache", cache="hit")
            # warm runs never touch the compile service, so its costmodel
            # hook never fires — re-emit the memoized executables' static
            # costs here (read-only, never fatal) so a warm run's ledger
            # is as roofline-renderable as a cold one's
            from .parallel.compile_service import _perf_armed
            if _perf_armed():
                from .analysis import costmodel
                costmodel.observe_executables(
                    {"A": jitted[0], "B": jitted[1]},
                    tag=repr(jit_key), run=run)
        from jax.sharding import NamedSharding, PartitionSpec as P

        d_sh = NamedSharding(mesh, P("design"))
        c_sh = NamedSharding(mesh, P("case"))
        # small per-turbine-variant tables: replicate; the per-chunk
        # gather index is design-sharded, so the gathered arrays land
        # design-sharded without collectives
        r_sh = NamedSharding(mesh, P())
        put_d = lambda x: jax.device_put(x, d_sh)
        put_c = lambda x: jax.device_put(x, c_sh)
        put_r = lambda x: jax.device_put(x, r_sh)
        # commit the shared per-case inputs once (uncommitted arrays would
        # re-transfer to the accelerator on every chunk call)
        zetas = put_c(zetas)
        betas = put_c(betas)

        pending_compile = None
        compile_sentinel = None
        if jitted is None and run.enabled:
            # XLA cost accounting: count backend compiles while the AOT
            # build runs, so compile_end events can tell a real compile
            # from a persistent-cache deserialization.  Only armed when
            # the ledger is on — the sentinel hooks jax logging/monitoring
            # and the off path must not touch global state.
            from .analysis.recompile import RecompileSentinel

            compile_sentinel = RecompileSentinel()
            compile_sentinel.__enter__()
        if jitted is None:
            # ---- split-program AOT build.  The chunk work is two XLA
            # programs instead of one fused jit:
            #   A: packed leaves -> solver params + design props (the
            #      vmapped design compiler), and
            #   B: params (+ per-case aero / turbine-variant tables) ->
            #      response metrics (the vmapped case solver).
            # Splitting exists for COLD-START latency, the number the
            # reference DOE workload actually pays (a fresh process per
            # sweep, raft/parametersweep.py:56-100): both programs are
            # submitted to the background compile service
            # (parallel/compile_service.py) the moment they are lowered —
            # the compiles run concurrently on worker threads (XLA
            # releases the GIL) or deserialize from the RAFT_TPU_EXEC_CACHE
            # serialized-executable cache, while the MAIN thread keeps
            # going (aero-servo tables, stack memo, resident upload,
            # checkpoint setup).  The sweep blocks only at first chunk
            # dispatch (`_join_compiles` below).  Execution cost is
            # unchanged — params is consumed on-device by B.
            solve_p = make_parametric_solver(
                static, n_iter=n_iter, with_health=run_health,
                tik_eps=hcfg["tik_eps"], tik_cond_tol=hcfg["tik_cond_tol"],
                resid_trace=run_trace)
            # nacelle positions for the acceleration channel (constant
            # across platform-geometry variants; per-variant along turbine
            # axes); the reported channel is the max over rotors, matching
            # what the WEIS Max_Nacelle_Acc aggregate reads
            z_hubs = jnp.asarray([float(r.r3[2]) for r in fowt.rotorList] or [0.0])
            w_j = jnp.asarray(fowt.w)

            @shape_contract("[c,h,1,6,nw],[c,r]->[c,h,6],[c,h]")
            def _metrics(Xi, zh):
                """Xi [chunk, ncase, 1, 6, nw]; zh [chunk, nrot]."""
                std = jnp.sqrt(0.5 * jnp.sum(jnp.abs(Xi[:, :, 0]) ** 2, axis=-1))
                # nacelle fore-aft acceleration: -w^2 (xi1 + z_hub*xi5)
                a_nac = (w_j**2) * (Xi[:, :, 0, 0, None, :]
                                    + zh[:, None, :, None] * Xi[:, :, 0, 4, None, :])
                a_std = jnp.sqrt(0.5 * jnp.sum(jnp.abs(a_nac) ** 2, axis=-1))
                return std, jnp.max(a_std, axis=-1)

            def _leaves(packed):
                return jax.tree_util.tree_unflatten(
                    treedef, unpack_leaves(packed, spec, n_leaves))

            def _postB(out, zh):
                """Metrics (+ health, + residual trace) from the
                double-vmapped solve."""
                if not run_health:
                    return _metrics(out, zh)
                if run_trace:
                    Xi, hb, tr = out  # tr: [chunk, ncase, n_iter]
                else:
                    Xi, hb = out  # hb leaves: [chunk, ncase]
                std, a_std = _metrics(Xi, zh)
                # escalate metric non-finiteness into the health flag so
                # a status-ok row can never carry NaN
                hb = hb._replace(
                    nonfinite=hb.nonfinite
                    | ~jnp.all(jnp.isfinite(std), axis=-1)
                    | ~jnp.isfinite(a_std))
                if run_trace:
                    return std, a_std, hb, tr
                return std, a_std, hb

            if mode in ("sel", "sel_wind"):
                def partA(packed, rna_table, av):
                    geoms, moor = _leaves(packed)
                    rna = jax.tree_util.tree_map(lambda x: x[av], rna_table)
                    params = jax.vmap(compile_one)(geoms, moor, rna)
                    return params.pop("props"), params
            else:
                def partA(packed):
                    geoms, moor = _leaves(packed)
                    params = jax.vmap(compile_one)(geoms, moor)
                    return params.pop("props"), params

            if mode == "plain":
                def partB(params, zetas, betas):
                    out = jax.vmap(jax.vmap(solve_p, in_axes=(None, 0, 0)),
                                   in_axes=(0, None, None))(params, zetas, betas)
                    zh = jnp.broadcast_to(z_hubs, (params["w"].shape[0],) + z_hubs.shape)
                    return _postB(out, zh)
            elif mode == "aero":
                def partB(params, zetas, betas, aero):
                    out = jax.vmap(jax.vmap(solve_p, in_axes=(None, 0, 0, 0)),
                                   in_axes=(0, None, None, None))(params, zetas, betas, aero)
                    zh = jnp.broadcast_to(z_hubs, (params["w"].shape[0],) + z_hubs.shape)
                    return _postB(out, zh)
            elif mode == "sel":
                def partB(params, zetas, betas, zh_table, av):
                    out = jax.vmap(jax.vmap(solve_p, in_axes=(None, 0, 0)),
                                   in_axes=(0, None, None))(params, zetas, betas)
                    return _postB(out, zh_table[av])
            else:  # sel_wind
                def partB(params, zetas, betas, sel, av):
                    aero_v = {"A": sel["A"][av], "B": sel["B"][av]}
                    out = jax.vmap(jax.vmap(solve_p, in_axes=(None, 0, 0, 0)),
                                   in_axes=(0, None, None, 0))(params, zetas, betas, aero_v)
                    return _postB(out, sel["zh"][av])

            # donate the per-chunk intermediates: argument 0 of A is
            # the gathered/packed chunk buffers (produced fresh per
            # chunk by the on-device gather or the host pack) and
            # argument 0 of B is A's params output — neither is read
            # again after the call, so XLA reuses their device memory
            # for outputs instead of allocating a second chunk's
            # worth.  The shared inputs (zetas/betas/variant tables/
            # resident batch) are NOT in argnum 0 and stay intact.
            # Donation composes with the explicit shardings: a donated
            # input is aliased only to an output of matching layout,
            # per shard.
            # shard_map, not bare GSPMD: letting the partitioner rewrite
            # the global HLO perturbs CPU codegen enough to move the last
            # bits (~1e-15 on the demo spar), breaking the bit-identity
            # contract with the single-device run.  Under shard_map each
            # shard compiles the SAME local program as the 1x1 mesh —
            # only the batch extent shrinks, which is bit-invariant here
            # (all reductions are within-design/within-case) — so the
            # mesh result is bit-identical to single-device.
            from jax.experimental.shard_map import shard_map

            dc = NamedSharding(mesh, P("design", "case"))
            pd, pc, pr, pdc = P("design"), P("case"), P(), P("design", "case")
            if mode in ("sel", "sel_wind"):
                inA = ([d_sh] * len(spec), r_sh, d_sh)
                inB = (d_sh, c_sh, c_sh, r_sh, d_sh)
                specA = ([pd] * len(spec), pr, pd)
                specB = (pd, pc, pc, pr, pd)
            else:
                inA = ([d_sh] * len(spec),)
                inB = ((d_sh, c_sh, c_sh) if mode == "plain"
                       else (d_sh, c_sh, c_sh, c_sh))
                specA = ([pd] * len(spec),)
                specB = ((pd, pc, pc) if mode == "plain"
                         else (pd, pc, pc, pc))
            shA = shard_map(partA, mesh=mesh, in_specs=specA,
                            out_specs=(pd, pd), check_rep=False)
            jA = jax.jit(shA, donate_argnums=(0,),
                         in_shardings=inA, out_shardings=(d_sh, d_sh))
            # the health pytree's leaves are [chunk, ncase] like the
            # metrics, so the same (design, case) sharding applies as
            # a pytree prefix
            outB_spec = (pdc, pdc, pdc) if run_health else (pdc, pdc)
            outB_sh = (dc, dc, dc) if run_health else (dc, dc)
            if run_trace:
                # the [chunk, ncase, n_iter] residual trace shards like
                # the metrics along its leading (design, case) axes
                outB_spec = outB_spec + (pdc,)
                outB_sh = outB_sh + (dc,)
            shB = shard_map(partB, mesh=mesh, in_specs=specB,
                            out_specs=outB_spec, check_rep=False)
            jB = jax.jit(shB, donate_argnums=(0,),
                         in_shardings=inB, out_shardings=outB_sh)
            sds = lambda sh, dt: jax.ShapeDtypeStruct(sh, dt)

            fdt = np.dtype(zetas.dtype)
            nw = static["nw"]
            packed_sds = [sds((chunk_size, sum(s for _, _, s in entries)),
                              np.dtype(dts)) for dts, entries in spec]
            if mode in ("sel", "sel_wind"):
                rna_sds = jax.tree_util.tree_map(
                    lambda x: sds((len(av_combos),) + tuple(x.shape), x.dtype),
                    rna_params_for(fowt))
                av_sds = sds((chunk_size,), np.dtype(np.int32))
                argsA = (packed_sds, rna_sds, av_sds)
            else:
                argsA = (packed_sds,)

            # trace serially on this thread (tracing is Python and holds
            # the GIL anyway); compile concurrently on worker threads.
            # Each thread also runs its executable ONCE on zero-filled
            # arguments: the first invocation pays a few seconds of
            # executable upload/initialization on a remote-chip runtime,
            # and absorbing it here overlaps it with the main thread's
            # aero-table work (the garbage outputs are discarded — a
            # zero-geometry solve just produces NaNs in dead buffers).
            # donation is best-effort: XLA aliases only the donated
            # buffers whose sizes match an output, and warns about the
            # rest on every lowering.  That partial coverage is the
            # expected steady state here (params has many more leaves
            # than B has outputs), not a bug worth a per-sweep warning.
            def _lower(j, *args):
                with warnings.catch_warnings():
                    warnings.filterwarnings(
                        "ignore", message="Some donated buffers were not usable")
                    return j.lower(*args)

            lA = _lower(jA, *argsA)

            # warm-exec (a discarded zero-argument run of the fresh
            # executable, absorbing any lazy backend initialization on
            # the worker thread) only pays when the main thread has
            # aero/variant table work to overlap it with; in 'plain'
            # mode the join happens almost immediately, so a dummy run
            # would simply extend the critical path by one chunk
            # execution.  Warm failures are best-effort: recorded on the
            # task and surfaced after the join.
            warm_exec = mode != "plain"

            def _zeros_like_sds(tree, put):
                return jax.tree_util.tree_map(
                    lambda s: put(np.zeros(s.shape, s.dtype)), tree)

            if mode in ("sel", "sel_wind"):
                def dummyA():
                    return (_zeros_like_sds(packed_sds, put_d),
                            _zeros_like_sds(rna_sds, put_r),
                            put_d(np.zeros((chunk_size,), np.int32)))
            else:
                def dummyA():
                    return (_zeros_like_sds(packed_sds, put_d),)

            # the serialized-executable cache entry is scoped by the full
            # executable identity (jit_key covers mode/placement/extents/
            # health trace) on top of the per-program StableHLO hash the
            # service adds — a changed trace can never hit a stale entry
            compile_service = CompileService(run=run, chaos=chaos_plan)
            pending_compile = {
                "A": compile_service.submit(
                    "A", lA, cache_tag=repr(jit_key),
                    warm_args_fn=dummyA if warm_exec else None),
            }

            # lowered.out_info leaves are OutInfo objects on recent JAX,
            # which .lower() rejects as abstract arguments — re-wrap as
            # plain ShapeDtypeStructs (jB carries explicit in_shardings)
            params_sds = jax.tree_util.tree_map(
                lambda o: jax.ShapeDtypeStruct(o.shape, o.dtype),
                lA.out_info[1])
            if bem_active:
                # the precomputed BEM leaves join partA's params at
                # dispatch (fresh per-chunk host slices, so partB's
                # donation stays safe); partB/case_solve presence-gate on
                # the keys, so lowering B against the extended dict is
                # what compiles the BEM consumption in
                nbh = len(bem_heads)
                params_sds = dict(params_sds)
                params_sds["Abem"] = sds((chunk_size, nw, 6, 6), fdt)
                params_sds["Bbem"] = sds((chunk_size, nw, 6, 6), fdt)
                params_sds["Xbre"] = sds((chunk_size, nbh, 6, nw), fdt)
                params_sds["Xbim"] = sds((chunk_size, nbh, 6, nw), fdt)
                params_sds["bem_h"] = sds((chunk_size, nbh), fdt)
            nrot = max(1, len(fowt.rotorList))
            if mode == "plain":
                argsB = (params_sds, zetas, betas)
            elif mode == "aero":
                argsB = (params_sds, zetas, betas,
                         {k: sds((n_cases, nw, 6, 6), fdt) for k in ("A", "B")})
            elif mode == "sel":
                argsB = (params_sds, zetas, betas,
                         sds((len(av_combos), nrot), fdt), av_sds)
            else:
                sel_sds = {k: sds((len(av_combos), n_cases, nw, 6, 6), fdt)
                           for k in ("A", "B")}
                sel_sds["zh"] = sds((len(av_combos), nrot), fdt)
                argsB = (params_sds, zetas, betas, sel_sds, av_sds)
            def dummyB():
                params_z = _zeros_like_sds(params_sds, put_d)
                if mode == "plain":
                    return (params_z, zetas, betas)
                if mode == "aero":
                    return (params_z, zetas, betas,
                            _zeros_like_sds(argsB[3], put_c))
                # sel / sel_wind: replicated variant table + design-sharded
                # gather index
                return (params_z, zetas, betas,
                        _zeros_like_sds(argsB[3], put_r),
                        put_d(np.zeros((chunk_size,), np.int32)))

            lB = _lower(jB, *argsB)
            pending_compile["B"] = compile_service.submit(
                "B", lB, cache_tag=repr(jit_key),
                warm_args_fn=dummyB if warm_exec else None)

        # the template memo entry exists as soon as the programs are in
        # flight (the compiled pair lands in it at the join); creating it
        # here lets the stack/resident memos below attach to it on the
        # SAME cold sweep instead of only after a warm repeat
        with _MEMO_LOCK:
            entry = _TEMPLATE_MEMO.get(memo_key)
            if (entry is None or entry["treedef"] != treedef
                    or entry.get("spec") != spec):
                entry = {"model": model, "fowt": fowt,
                         "compile_one": compile_one, "static": static,
                         "treedef": treedef, "spec": spec, "jitted": {}}
                _TEMPLATE_MEMO[memo_key] = entry
            while len(_TEMPLATE_MEMO) > _TEMPLATE_MEMO_MAX:
                _TEMPLATE_MEMO.pop(next(iter(_TEMPLATE_MEMO)))

        def _join_compiles():
            """First-dispatch join on the background compile pipeline:
            returns the (cA, cB) chunk executables, blocking only for
            whatever compile time the host work above failed to hide
            (ledger: `compile_overlap`; profiling: `.../wait_executable`).
            Idempotent — the memoized pair is returned on repeat calls."""
            nonlocal jitted
            if jitted is not None:
                return jitted
            built = wait_for_executables(pending_compile, run=run)
            if compile_sentinel is not None:
                compile_sentinel.__exit__(None, None, None)
                for key, fname in (("A", "partA"), ("B", "partB")):
                    # log-derived names wrap the function ("jit(partA)")
                    n_xla = sum(
                        v for k, v in
                        compile_sentinel.compiles_by_name.items()
                        if fname in k)
                    task = pending_compile[key]
                    run.emit("compile_end", key=key, seconds=task.seconds,
                             cache=("exec_cache"
                                    if task.source == "exec_cache"
                                    else "miss" if n_xla else "hit"),
                             xla_compiles=n_xla, source=task.source)
            # surfaced unconditionally: a failed warm run usually means
            # every chunk pays the upload cost it was meant to hide, and
            # headless/CI runs (display=0) must see that too
            for key in sorted(pending_compile):
                err = pending_compile[key].warm_error
                if err is None:
                    continue
                msg = (f"sweep: warm-exec of part {key} failed "
                       f"({type(err).__name__}: {err}); first chunk "
                       "will pay executable initialization")
                obs_log.warn(_LOG, msg, RuntimeWarning)
                if display:
                    obs_log.display(_LOG, msg)
            cA_, cB_ = built.get("A"), built.get("B")
            if isinstance(cA_, Exception) or isinstance(cB_, Exception):
                # AOT failed (e.g. an exotic sharding/backend combination):
                # fall back to the plain jits, which compile inline at the
                # first chunk call
                if display:
                    obs_log.display(
                        _LOG,
                        f"sweep: AOT compile failed ({cA_!r} / {cB_!r}); "
                        "falling back to inline jit")
                cA_, cB_ = jA, jB
            jitted = (cA_, cB_)
            with _MEMO_LOCK:
                entry = _TEMPLATE_MEMO.get(memo_key)
                if entry is not None and entry.get("spec") == spec:
                    entry["jitted"][jit_key] = jitted
            return jitted

        # main thread (overlapped with the compiles above): aero-servo
        # impedance for the shared-turbine case, or the per-turbine-variant
        # tables (model builds + rotor BEM) along turbine axes
        aero = None
        sel_variants = None
        if compile_only:
            pass  # no chunk will run; the variant tables are execution-only
        elif mode == "aero":
            with profiling.phase("sweep/aero"):
                aero = put_c(case_aero_params(fowt, wind))
        elif aero_axes:
            rna_l, zh_l, A_l, B_l = [], [], [], []
            for c in av_combos:
                fv = _turbine_variant_fowt(fowt, base_design, axes, aero_axes, c)
                rna_l.append(jax.tree_util.tree_map(np.asarray, rna_params_for(fv)))
                zh_l.append(np.asarray([float(r.r3[2]) for r in fv.rotorList] or [0.0]))
                if wind is not None:
                    av = case_aero_params(fv, wind)
                    A_l.append(np.asarray(av["A"]))
                    B_l.append(np.asarray(av["B"]))
            sel_variants = {
                "rna": jax.tree_util.tree_map(
                    lambda *xs: np.stack(xs), *rna_l),
                "zh": np.stack(zh_l),
            }
            if wind is not None:
                sel_variants["A"] = np.stack(A_l)
                sel_variants["B"] = np.stack(B_l)
            sel_variants = put_r(sel_variants)

        # batched BEM precompute: ONE radiation/diffraction solve per
        # (design batch, ω grid, heading set) — overlapped with the
        # background chunk compiles above, like the aero tables.  The
        # result is host numpy [n_designs, ...] leaves sliced per chunk
        # at dispatch (the resident executor keeps the packed GEOMETRY
        # on device; the BEM leaves are small — 2·nw·36 + 2·nbh·6·nw
        # floats per design — so per-chunk H2D is noise).  Memoized in
        # the template memo next to the stack, keyed by the stacked
        # batch identity plus the solved heading set, so warm repeat
        # sweeps skip the solve entirely.
        bem_host = None
        if bem_active and not compile_only:
            bem_key = ((stack_key, bem_heads)
                       if stack_key is not None else None)
            bcache = None
            with _MEMO_LOCK:
                entry = _TEMPLATE_MEMO.get(memo_key)
                if (bem_key is not None and entry is not None
                        and entry.get("treedef") == treedef
                        and entry.get("spec") == spec):
                    bcache = entry.setdefault("bem", {})
                    bem_host = bcache.get(bem_key)
            if bem_host is None:
                from .hydro.bem_batch import solve_design_batch
                bdt = np.dtype(zetas.dtype)
                with profiling.phase("sweep/bem"):
                    t0 = time.perf_counter()
                    bem_host = solve_design_batch(
                        fowt, treedef, stacked, n_designs,
                        np.asarray(fowt.w), np.asarray(fowt.k),
                        headings_deg=bem_heads)
                    bem_host = {k: np.ascontiguousarray(v, dtype=bdt)
                                for k, v in bem_host.items()}
                run.emit("bem_precompute", cache="miss",
                         designs=n_designs, nw=int(static["nw"]),
                         headings=len(bem_heads),
                         seconds=round(time.perf_counter() - t0, 6))
                if bcache is not None:
                    with _MEMO_LOCK:
                        while len(bcache) >= 2:
                            bcache.pop(next(iter(bcache)))
                        bcache[bem_key] = bem_host
            else:
                run.emit("bem_precompute", cache="hit",
                         designs=n_designs, headings=len(bem_heads))

        if compile_only:
            # precompile(): join, memoize (and, with RAFT_TPU_EXEC_CACHE,
            # serialize) the executables, report — dispatch nothing
            _join_compiles()
            report = {"mode": mode, "chunk_size": chunk_size,
                      "n_cases": n_cases, "n_designs": n_designs}
            if pending_compile is None:
                report["cache"] = "memo"
                report["compiled"] = {}
            else:
                report["cache"] = None
                report["compiled"] = {
                    k: {"source": t.source,
                        "seconds": (round(t.seconds, 6)
                                    if t.seconds is not None else None)}
                    for k, t in pending_compile.items()}
            return report

        # cA/cB are resolved by the first-dispatch join at the top of the
        # chunk loop — everything in between runs while XLA compiles
        cA = cB = None
        if cached_stack is None and stack_key is not None:
            with _MEMO_LOCK:
                entry = _TEMPLATE_MEMO.get(memo_key)
                if entry is not None and entry.get("treedef") == treedef:
                    stacks = entry.setdefault("stacks", {})
                    while len(stacks) >= 4:
                        stacks.pop(next(iter(stacks)))
                    stacks[stack_key] = (stacked, treedef, aero_axes)

        # input-validity premark: designs whose stacked leaves carry
        # NaN/Inf are flagged NAN even if the solve happens to return
        # finite garbage for them
        input_ok = variant_finite_mask(stacked)

        # ---- device-resident executor state (parallel/executor.py).
        # The whole packed variant batch is uploaded ONCE, chunk-major:
        # [n_chunks, chunk_size, width] buffers laid out P(None,
        # "design") on the mesh, so every chunk's rows already live on
        # the shard that will compute them and per-chunk selection
        # (executor.chunk_selector, a dynamic slice by a traced chunk
        # number) is shard-local — no collectives, no host copy, no H2D.
        # A design-sharded flat batch gathered by arbitrary global
        # indices would instead make GSPMD insert all-to-alls per chunk.
        # Cached in the template memo (keyed like the stack memo plus
        # mesh placement and chunk tiling), so a repeat sweep re-uploads
        # nothing.
        resident = None
        if ecfg["resident"]:
            rkey = ((stack_key, place_sig, chunk_size)
                    if stack_key is not None else None)
            rcache = None
            with _MEMO_LOCK:
                entry = _TEMPLATE_MEMO.get(memo_key)
                if (rkey is not None and entry is not None
                        and entry.get("treedef") == treedef
                        and entry.get("spec") == spec):
                    rcache = entry.setdefault("resident", {})
                    resident = rcache.get(rkey)
            if resident is None:
                upload_err = None
                try:
                    if chaos_plan is not None:
                        chaos_plan.maybe_raise("oom_upload")
                    with profiling.phase("sweep/resident_upload"):
                        n_chunks_r = -(-n_designs // chunk_size)
                        chunk_idx = np.empty((n_chunks_r, chunk_size),
                                             dtype=np.int64)
                        for k in range(n_chunks_r):
                            c_start = k * chunk_size
                            c_stop = min(c_start + chunk_size, n_designs)
                            # identical padding rule to the chunk loop below
                            row = np.arange(c_start, c_start + chunk_size)
                            row[c_stop - c_start:] = c_stop - 1
                            chunk_idx[k] = row
                        cm_sh = NamedSharding(mesh, P(None, "design"))
                        resident = [jax.device_put(b[chunk_idx], cm_sh)
                                    for b in pack_rows(stacked, spec,
                                                       np.arange(n_designs))]
                except Exception as e:  # noqa: BLE001 - OOM downgrades only
                    # an allocation failure on the resident batch is
                    # survivable: the per-chunk host-packing path computes
                    # the identical results with a fraction of the
                    # footprint.  Anything that isn't an OOM re-raises.
                    if not elastic.is_oom(e):
                        raise
                    upload_err = e
                    resident = None
                if upload_err is not None:
                    run.emit("capability_fallback", reason="resident_oom",
                             detail=f"{type(upload_err).__name__}: "
                                    f"{upload_err}")
                    obs_log.warn(
                        _LOG,
                        f"sweep: resident batch upload failed "
                        f"({type(upload_err).__name__}: {upload_err}); "
                        f"falling back to per-chunk host packing",
                        RuntimeWarning)
                elif run.enabled:
                    per_dev = obs_ledger.shard_bytes(resident)
                    run.emit("transfer", direction="h2d",
                             bytes=obs_ledger.tree_nbytes(resident),
                             what="resident_batch",
                             **({"per_device": per_dev} if per_dev else {}))
                    obs_ledger.emit_device_memory(run, device=devices,
                                                  what="resident_upload")
                if resident is not None and rcache is not None:
                    with _MEMO_LOCK:
                        while len(rcache) >= 2:
                            rcache.pop(next(iter(rcache)))
                        rcache[rkey] = resident

        # static IR audit of the chunk-gather selector (graftaudit):
        # lowers the selector over the real resident batch — tracing
        # only, no XLA compile; the implicit jit compile at first
        # dispatch below is unchanged — and checks the executor's
        # shard-local contract (NO collectives in chunk selection)
        if resident is not None:
            from .parallel.compile_service import _audit_armed
            if _audit_armed():
                from .analysis import graftaudit
                graftaudit.observe_gather(
                    chunk_selector(d_sh), (resident, np.int32(0)), run=run)

        # flight-recorder anomaly capture: armed only with a bundle
        # directory, and only on this batched path — a replay bundle
        # re-runs its single design through sweep(design, axes=[], ...),
        # which IS this path, so captures replay through the same traced
        # programs that produced them
        recorder = None
        if fcfg["enabled"] and fcfg["dir"]:
            from .obs.flightrec import Recorder
            recorder = Recorder(
                base_design=base_design, axes=axes, combos=combos,
                sea_states=sea_states, wind=wind, n_iter=n_iter,
                hcfg=hcfg, fcfg=fcfg, chunk_size=chunk_local, run=run,
                stacked=stacked)

        # coalescing background checkpoint persistence: the chunk loop
        # submits state snapshots and never blocks on np.savez; close()
        # in the finally below guarantees the final (complete) state is
        # on disk before sweep() returns, so resume semantics and the
        # end-of-sweep file contents are exactly the synchronous path's.
        ckpt_writer = None
        if checkpoint:
            ckpt_writer = CheckpointWriter(
                lambda st: _save_checkpoint(
                    checkpoint, sig, *st,
                    mesh_shape=tuple(mesh.devices.shape),
                    chaos=chaos_plan),
                on_write=(lambda secs, err: run.emit(
                    "checkpoint_flush", seconds=secs, ok=err is None))
                if run.enabled else None)

        def _submit_ckpt():
            # snapshot copies: the writer serializes at an arbitrary
            # later time while the loop keeps mutating the originals
            ckpt_writer.submit((results.copy(), done.copy(),
                                {k: v.copy() for k, v in props.items()},
                                nacelle_acc.copy(), status.copy(),
                                health_resid.copy(), health_cond.copy()))

        # elastic-execution machinery (robust.elastic): watchdog
        # deadlines, graceful drain, device-loss re-meshing.  All of it
        # is host-side scheduling — disarmed (the defaults) none of it
        # touches the chunk hot path beyond a flag check per chunk.
        wd = elastic.Watchdog(rcfg, run=run) if rcfg["watchdog"] else None
        remesh_armed = bool(rcfg["remesh"]) and len(devices) > 1
        guard = elastic.ShutdownGuard(mode=rcfg["graceful"])
        dispatched_at = {}  # chunk start -> dispatch perf_counter

        def _remesh_required(err):
            # state is captured by reference: by the time sweep()
            # re-enters, the drain/flush in the finally below has
            # quiesced the isolation worker and the checkpoint writer
            return elastic.RemeshRequired(
                error=err, devices=list(devices),
                state={"results": results, "nacelle_acc": nacelle_acc,
                       "props": props, "done": done, "status": status,
                       "health_resid": health_resid,
                       "health_cond": health_cond,
                       "conv_trace": conv_trace,
                       "chaos_plan": chaos_plan})

        with profiling.phase("sweep/chunks"), maybe_trace("chunks"), guard:
            # wait-for-executable: the background compiles (or exec-cache
            # deserializations) submitted in the plan phase are joined
            # HERE, at first chunk dispatch — the stall (if any) is the
            # residual cold-start cost after the host overlap window, and
            # lands in profiling as sweep/chunks/wait_executable with a
            # matching `compile_overlap` ledger event
            cA, cB = _join_compiles()
            # software-pipelined with bounded depth: chunk k+1's gather
            # and executables are queued before chunk k's results are
            # fetched, hiding the host->device->host round trips behind
            # execution (which matters when the chip sits behind a network
            # tunnel) — but never more than `pipeline_depth` chunks are in
            # flight, so device memory stays bounded and per-chunk
            # checkpoint commits lag at most depth-1 chunks behind
            # dispatch.  Depth 1 is fully synchronous; results are
            # bit-identical at every depth (the traced programs and their
            # execution order per design are unchanged).
            pending = []

            def _dispatch(idx, chunk_no=None):
                """Queue one padded chunk; returns un-fetched device
                results (std, a_std, props, health-or-None).
                ``chunk_no`` selects the pre-staged resident chunk;
                ``None`` (quarantine re-execution, RAFT_TPU_RESIDENT=0)
                host-packs ``idx`` instead."""
                if chaos_plan is not None and chunk_no is not None:
                    # device_lost fires on pipeline dispatches only:
                    # quarantine re-executions (chunk_no None) stay
                    # clean so a non-loss retry path cannot trip it
                    chaos_plan.maybe_raise(
                        "device_lost", chunk=chunk_no,
                        device_ids=[int(d.id) for d in devices])
                dispatch = functools.partial(_dispatch_real,
                                             chunk_no=chunk_no)
                if _CHUNK_EXEC_HOOK is not None:
                    return _CHUNK_EXEC_HOOK(np.asarray(idx), dispatch)
                return dispatch(idx)

            def _dispatch_real(idx, chunk_no=None):
                with profiling.phase("gather"):
                    if resident is not None and chunk_no is not None:
                        # shard-local chunk selection from the
                        # chunk-major resident batch (fresh output
                        # buffers -> donatable to A); the traced-scalar
                        # chunk number keeps it ONE compile for all
                        # chunks, and the process-wide selector memo
                        # keeps warm repeat sweeps at zero compiles
                        packed = chunk_selector(d_sh)(
                            resident, np.int32(chunk_no))
                    else:
                        # host fancy-index pack + per-chunk transfer;
                        # device_put commits exactly the executables'
                        # design sharding, so no new XLA programs
                        packed = [put_d(b) for b in pack_rows(stacked, spec, idx)]
                def _with_bem(params):
                    # thread the precomputed BEM leaves into partB's
                    # params: fresh per-chunk host slices through put_d,
                    # so B's argnum-0 donation never aliases a buffer
                    # that is read again (quarantine re-executions slice
                    # again, so they are covered identically)
                    if bem_host is None:
                        return params
                    params = dict(params)
                    rows = np.asarray(idx)
                    for kb in ("Abem", "Bbem", "Xbre", "Xbim", "bem_h"):
                        params[kb] = put_d(bem_host[kb][rows])
                    return params

                with profiling.phase("compute"):
                    if mode == "plain":
                        pr, params = cA(packed)
                        outB = cB(_with_bem(params), zetas, betas)
                    elif mode == "aero":
                        pr, params = cA(packed)
                        outB = cB(_with_bem(params), zetas, betas, aero)
                    else:
                        av_dev = put_d(aero_idx[idx])
                        pr, params = cA(packed, sel_variants["rna"], av_dev)
                        if mode == "sel":
                            outB = cB(_with_bem(params), zetas, betas,
                                      sel_variants["zh"], av_dev)
                        else:
                            outB = cB(_with_bem(params), zetas, betas,
                                      {k: sel_variants[k] for k in ("A", "B", "zh")},
                                      av_dev)
                tr = None
                if run_trace:
                    std, a_std, hb, tr = outB
                elif run_health:
                    std, a_std, hb = outB
                else:
                    (std, a_std), hb = outB, None
                # kick off the device->host copies now: they overlap the
                # next chunk's execution, and the commit-side np.asarray
                # finds the bytes already on the host.  The dispatch
                # tuple stays a 4-tuple whenever the trace is off so the
                # _CHUNK_EXEC_HOOK test seam (and anything else unpacking
                # it) sees the historical arity.
                return start_host_fetch(
                    (std, a_std, pr, hb) + ((tr,) if run_trace else ()))

            def _classify_rows(rows_idx, std_rows, a_std_rows, hb_rows):
                """int8 per-design status for fetched numpy chunk rows."""
                fin = (np.isfinite(std_rows).all(axis=-1)
                       & np.isfinite(a_std_rows))  # [n, ncase]
                st = np.where(fin, np.int8(STATUS_OK),
                              np.int8(STATUS_NAN)).astype(np.int8)
                if hb_rows is not None:
                    st = np.maximum(st, classify_health(
                        SolveHealth(**hb_rows),
                        hcfg["resid_tol"], hcfg["cond_tol"]))
                st = reduce_design_status(st)  # worst over cases -> [n]
                return np.maximum(
                    st, np.where(input_ok[rows_idx], np.int8(STATUS_OK),
                                 np.int8(STATUS_NAN)))

            def _store_rows(rows_idx, std_rows, a_std_rows, pr_rows, hb_rows,
                            tr_rows=None):
                """Write fetched rows + their status into the result
                arrays (rows_idx: absolute design indices)."""
                results[rows_idx] = std_rows
                nacelle_acc[rows_idx] = a_std_rows
                for k in props:
                    props[k][rows_idx] = pr_rows[k]
                if hb_rows is not None:
                    health_resid[rows_idx] = np.max(hb_rows["resid"], axis=-1)
                    health_cond[rows_idx] = np.min(hb_rows["cond"], axis=-1)
                if tr_rows is not None:
                    conv_trace[rows_idx] = tr_rows
                    if run.enabled:
                        # worst-over-cases per design: one entry per row
                        iters = np.max(iterations_to_tolerance(
                            tr_rows, hcfg["resid_tol"]), axis=-1)
                        final = np.max(tr_rows[..., -1], axis=-1)
                        run.emit(
                            "convergence_summary",
                            chunk=int(rows_idx[0]) // chunk_size,
                            n_iter=int(n_iter),
                            designs=[int(i) for i in rows_idx],
                            iters=[int(i) for i in iters],
                            # JSON has no Inf/NaN: non-finite -> None
                            final_resid=[float(r) if np.isfinite(r) else None
                                         for r in final])
                status[rows_idx] = _classify_rows(rows_idx, std_rows,
                                                  a_std_rows, hb_rows)
                if run.enabled:
                    st_rows = status[rows_idx]
                    for code in np.unique(st_rows):
                        if code != STATUS_OK:
                            run.emit(
                                "status_transition",
                                designs=[int(i) for i
                                         in rows_idx[st_rows == code]],
                                to=STATUS_NAMES.get(int(code), "?"))
                if recorder is not None:
                    st_rows = status[rows_idx]
                    for j in np.flatnonzero(st_rows >= recorder.severity):
                        rec = {"std": std_rows[j], "a_std": a_std_rows[j]}
                        if hb_rows is not None:
                            rec["health"] = {k: v[j]
                                             for k, v in hb_rows.items()}
                        if tr_rows is not None:
                            rec["resid_trace"] = tr_rows[j]
                        recorder.capture(int(rows_idx[j]), trigger="status",
                                         status=int(st_rows[j]),
                                         recorded=rec)
                done[rows_idx] = True
                if ckpt_writer is not None:
                    _submit_ckpt()

            def _commit(entry):
                start, stop, n_real, std, a_std, pr, hb = entry[:7]
                tr = entry[7] if len(entry) > 7 else None
                if chaos_plan is not None:
                    # fetch-boundary seams: a hung d2h copy and a
                    # poisoned fetch both surface here, where the
                    # watchdog (when armed) can cut them loose
                    chaos_plan.maybe_hang(start // chunk_size)
                    chaos_plan.maybe_raise("poison_fetch",
                                           chunk=start // chunk_size)
                with profiling.phase("fetch"):
                    hb_rows = None
                    if hb is not None:
                        hb_rows = {k: np.asarray(v)[:n_real]
                                   for k, v in hb._asdict().items()}
                    tr_rows = np.asarray(tr)[:n_real] if tr is not None else None
                    std_rows = np.asarray(std)[:n_real]
                    a_std_rows = np.asarray(a_std)[:n_real]
                    pr_rows = {k: np.asarray(pr[k])[:n_real] for k in props}
                if run.enabled:
                    nb = (std_rows.nbytes + a_std_rows.nbytes
                          + sum(v.nbytes for v in pr_rows.values())
                          + (sum(v.nbytes for v in hb_rows.values())
                             if hb_rows is not None else 0)
                          + (tr_rows.nbytes if tr_rows is not None else 0))
                    # per-shard split of the device-side result buffers:
                    # each mesh member streamed its shard back
                    # independently (copy_to_host_async is per-shard)
                    per_dev = obs_ledger.shard_bytes((std, a_std, pr, hb, tr))
                    run.emit("chunk_fetch", chunk=start // chunk_size,
                             bytes=int(nb),
                             **({"per_device": per_dev} if per_dev else {}))
                with profiling.phase("commit"):
                    _store_rows(np.arange(start, stop), std_rows, a_std_rows,
                                pr_rows, hb_rows, tr_rows)
                if run.enabled:
                    n_done = int(done.sum())
                    run.emit("chunk_commit", chunk=start // chunk_size,
                             done=n_done, n_designs=n_designs,
                             eta_s=run.elapsed() * (n_designs - n_done)
                             / max(n_done, 1))
                if display:
                    obs_log.display(
                        _LOG,
                        f"sweep: designs {start+1}-{stop}/{n_designs} done")

            def _exec_rows(sub_idx):
                """Quarantine-runner callable, watchdog-guarded when the
                watchdog is armed (a hung re-execution must not wedge
                the isolation worker either)."""
                if wd is None:
                    return _exec_rows_raw(sub_idx)
                return wd.guard(functools.partial(_exec_rows_raw, sub_idx))

            def _exec_rows_raw(sub_idx):
                """Quarantine-runner body: arbitrary-length design
                index array -> fetched numpy row dict.  Pads with the
                last index so the SAME compiled chunk executables serve
                every bisection level (no new XLA programs)."""
                sub_idx = np.asarray(sub_idx, dtype=np.int64)
                n_r = sub_idx.size
                idx = np.full(chunk_size, sub_idx[-1], dtype=np.int64)
                idx[:n_r] = sub_idx
                out = _dispatch(idx)
                std, a_std, pr, hb = out[:4]
                tr = out[4] if len(out) > 4 else None
                rows = {"std": np.asarray(std)[:n_r],
                        "a_std": np.asarray(a_std)[:n_r],
                        **{f"prop_{k}": np.asarray(pr[k])[:n_r]
                           for k in props}}
                if hb is not None:
                    for k, v in hb._asdict().items():
                        rows[k] = np.asarray(v)[:n_r]
                if tr is not None:
                    rows["resid_trace"] = np.asarray(tr)[:n_r]
                return rows

            isolator = FaultIsolator()

            def _isolate(start, stop, err):
                """A chunk raised (dispatch or fetch): emit the fault
                synchronously (deterministic ledger/warning order), then
                hand the retry-then-bisect re-execution to the isolation
                worker — the main loop keeps dispatching, so one shard's
                fault never stalls the other shards' pipelines.  The
                single worker preserves the single-threaded isolation
                semantics (faulted chunks isolate in submission order);
                its errors re-raise at ``drain()`` below."""
                run.emit("chunk_fault", start=start, stop=stop,
                         error=f"{type(err).__name__}: {err}")
                obs_log.warn(
                    _LOG,
                    f"sweep: chunk {start}-{stop} raised "
                    f"({type(err).__name__}: {err}); isolating faults",
                    RuntimeWarning)
                isolator.submit(functools.partial(_isolate_body, start, stop))

            def _isolate_body(start, stop):
                rows_idx = np.arange(start, stop)
                # align bisection splits to the per-shard chunk extent:
                # sub-ranges then keep every design at the same local
                # row position (j % chunk_local) it held in the original
                # dispatch, so healthy rows recovered by bisection are
                # bit-identical to an unfaulted run — and to the
                # single-device bisection of the same fault
                on_q = None
                if recorder is not None:
                    def on_q(design_idx, err):
                        recorder.capture(design_idx, trigger="quarantine",
                                         status=int(STATUS_QUARANTINED),
                                         error=err)
                merged, quarantined = run_isolated(
                    _exec_rows, rows_idx, retries=1, display=display,
                    align=chunk_local, on_quarantine=on_q,
                    backoff=rcfg["retry_backoff_s"],
                    backoff_max=rcfg["retry_backoff_max_s"],
                    raise_on=(elastic.is_device_loss if remesh_armed
                              else None))
                ok = ~quarantined
                if merged is not None and ok.any():
                    hb_rows = None
                    if "resid" in merged:
                        hb_rows = {k: merged[k][ok] for k in
                                   ("resid", "cond", "nonfinite", "n_fallback")}
                    tr_rows = (merged["resid_trace"][ok]
                               if "resid_trace" in merged else None)
                    _store_rows(rows_idx[ok], merged["std"][ok],
                                merged["a_std"][ok],
                                {k: merged[f"prop_{k}"][ok] for k in props},
                                hb_rows, tr_rows)
                status[rows_idx[quarantined]] = STATUS_QUARANTINED
                if run.enabled and quarantined.any():
                    bad = [int(i) for i in rows_idx[quarantined]]
                    run.emit("design_quarantined", designs=bad)
                    run.emit("status_transition", designs=bad,
                             to=STATUS_NAMES.get(int(STATUS_QUARANTINED), "?"))
                done[rows_idx] = True
                if ckpt_writer is not None:
                    _submit_ckpt()
                if display:
                    obs_log.display(
                        _LOG,
                        f"sweep: designs {start+1}-{stop}/{n_designs} done "
                        f"({int(quarantined.sum())} quarantined)")

            def _safe_commit(entry):
                # dispatch is async: a poison chunk often raises only at
                # the device->host fetch, i.e. here rather than in
                # _dispatch.  With the watchdog armed the fetch runs
                # under the remaining share of the chunk's deadline
                # (dispatch->fetch, so pipeline residency counts).
                try:
                    if wd is None:
                        _commit(entry)
                    else:
                        wd.guard(functools.partial(_commit, entry),
                                 chunk=entry[0] // chunk_size,
                                 since=dispatched_at.pop(entry[0], None))
                except Exception as e:  # noqa: BLE001 - isolation boundary
                    if remesh_armed and elastic.is_device_loss(e):
                        raise _remesh_required(e) from e
                    _isolate(entry[0], entry[1], e)

            try:
                for start in range(0, n_designs, chunk_size):
                    if guard.stop_requested:
                        # stop dispatching; in-flight entries drain
                        # below and the finally flushes the checkpoint,
                        # then SweepPreempted is raised after the block
                        break
                    stop = min(start + chunk_size, n_designs)
                    if done[start:stop].all():
                        continue
                    if chaos_plan is not None:
                        # self-SIGTERM at a seeded chunk boundary: the
                        # flag lands before the next iteration's check,
                        # so this chunk still dispatches and commits
                        chaos_plan.maybe_preempt(start // chunk_size)
                    # pad a short final chunk by repeating the last design so
                    # every chunk shares one leading shape (a second XLA compile
                    # would cost more than the padded rows; padded results are
                    # discarded)
                    n_real = stop - start
                    idx = np.arange(start, start + chunk_size)
                    idx[n_real:] = stop - 1
                    run.emit("chunk_dispatch", chunk=start // chunk_size,
                             start=start, stop=stop, n_real=n_real,
                             in_flight=len(pending) + 1,
                             devices=[int(d.id) for d in devices])
                    if wd is not None:
                        dispatched_at[start] = time.perf_counter()
                    try:
                        entry = (start, stop, n_real) + _dispatch(
                            idx, start // chunk_size)
                    except Exception as e:  # noqa: BLE001 - isolation boundary
                        if remesh_armed and elastic.is_device_loss(e):
                            raise _remesh_required(e) from e
                        _isolate(start, stop, e)
                        continue
                    pending.append(entry)
                    while len(pending) >= pipeline_depth:
                        _safe_commit(pending.pop(0))
                for entry in pending:
                    _safe_commit(entry)
            finally:
                # join the isolation worker first (it stores results and
                # submits checkpoints), THEN flush the final checkpoint
                # snapshot — the on-disk file then reflects every
                # committed AND every quarantined chunk, same as the old
                # synchronous saves.  drain() re-raises any unexpected
                # isolation error on this thread; a device loss that
                # surfaced inside the isolation worker (run_isolated's
                # raise_on lets it through) converts to the same
                # RemeshRequired as a loop-side loss.
                try:
                    isolator.drain()
                except Exception as e:  # noqa: BLE001 - remesh boundary
                    if remesh_armed and elastic.is_device_loss(e):
                        raise _remesh_required(e) from e
                    raise
                finally:
                    if ckpt_writer is not None:
                        ckpt_writer.close()
        if guard.stop_requested:
            # graceful shutdown: everything in flight is committed and
            # the checkpoint writer has flushed — exit resumable
            n_done = int(done.sum())
            run.emit("preempt", signal=guard.signal_name, done=n_done,
                     n_designs=n_designs, checkpoint=checkpoint or None)
            raise elastic.SweepPreempted(guard.signum,
                                         checkpoint=checkpoint,
                                         done=n_done, total=n_designs)
        if run.enabled:
            obs_ledger.emit_device_memory(run, device=devices,
                                          what="post_chunks")
        return _finalize()

    # ----- fallback: per-variant model compile, batched device solve -----
    run.emit("plan", mode="fallback", n_chunks=-(-n_designs // chunk_size),
             chunk_size=chunk_size)
    if compile_only:
        # the per-variant fallback builds a fresh Model per design at
        # execution time — there is no chunk executable to pre-bake
        return {"mode": "fallback", "chunk_size": chunk_size,
                "n_cases": n_cases, "n_designs": n_designs,
                "cache": None, "compiled": {}}
    zetas, betas = _sea_state_waves(fowt, sea_states)
    aero = case_aero_params(fowt, wind) if wind is not None else None
    batched = None
    for start in range(0, n_designs, chunk_size):
        stop = min(start + chunk_size, n_designs)
        if done[start:stop].all():
            continue

        params_list = []
        row_idx = []
        static = template = None
        for ic in range(start, stop):
            # the per-variant Model build runs arbitrary host geometry
            # code per design — the natural fault boundary on this path:
            # a design that cannot even build is quarantined, not fatal
            try:
                p, static, template = _compile_variant(base_design, axes, combos[ic], device)
            except Exception as e:  # noqa: BLE001 - isolation boundary
                obs_log.warn(
                    _LOG,
                    f"sweep: design {ic} {combos[ic]!r} failed to build "
                    f"({type(e).__name__}: {e}); quarantined",
                    RuntimeWarning)
                status[ic] = STATUS_QUARANTINED
                run.emit("design_quarantined", designs=[int(ic)])
                done[ic] = True
                continue
            params_list.append(p)
            row_idx.append(ic)
            if display:
                obs_log.display(
                    _LOG, f"compiled design {ic+1}/{n_designs}: {combos[ic]}")
        if not params_list:
            continue
        n_real = len(params_list)
        if n_designs > chunk_size:
            params_list += [params_list[-1]] * (chunk_size - n_real)

        if batched is None:
            solve_p = make_parametric_solver(
                static, n_iter=n_iter, with_health=run_health,
                tik_eps=hcfg["tik_eps"], tik_cond_tol=hcfg["tik_cond_tol"],
                resid_trace=run_trace)
            if aero is None:
                batched = jax.jit(jax.vmap(jax.vmap(solve_p, in_axes=(None, 0, 0)),
                                           in_axes=(0, None, None)))
            else:
                batched = jax.jit(jax.vmap(jax.vmap(solve_p, in_axes=(None, 0, 0, 0)),
                                           in_axes=(0, None, None, None)))

        params_stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)
        if aero is None:
            out = batched(params_stacked, zetas, betas)  # Xi [chunk, ncase, 1, 6, nw]
        else:
            out = batched(params_stacked, zetas, betas, aero)
        tr = None
        if run_trace:
            Xi, hb, tr = out
        elif run_health:
            Xi, hb = out
        else:
            hb = None
            Xi = out
        ridx = np.asarray(row_idx)
        rows = np.asarray(
            jnp.sqrt(0.5 * jnp.sum(jnp.abs(Xi[:, :, 0]) ** 2, axis=-1)))[:n_real]
        results[ridx] = rows
        done[ridx] = True
        st = np.where(np.isfinite(rows).all(axis=-1), np.int8(STATUS_OK),
                      np.int8(STATUS_NAN)).astype(np.int8)  # [n_real, ncase]
        if hb is not None:
            hb_rows = {k: np.asarray(v)[:n_real]
                       for k, v in hb._asdict().items()}
            st = np.maximum(st, classify_health(
                SolveHealth(**hb_rows), hcfg["resid_tol"], hcfg["cond_tol"]))
            health_resid[ridx] = np.max(hb_rows["resid"], axis=-1)
            health_cond[ridx] = np.min(hb_rows["cond"], axis=-1)
        if tr is not None:
            tr_rows = np.asarray(tr)[:n_real]
            conv_trace[ridx] = tr_rows
            if run.enabled:
                run.emit(
                    "convergence_summary",
                    chunk=start // chunk_size, n_iter=int(n_iter),
                    designs=[int(i) for i in ridx],
                    iters=[int(i) for i in np.max(iterations_to_tolerance(
                        tr_rows, hcfg["resid_tol"]), axis=-1)],
                    final_resid=[float(r) if np.isfinite(r) else None
                                 for r in np.max(tr_rows[..., -1], axis=-1)])
        status[ridx] = reduce_design_status(st)

        if checkpoint:
            _save_checkpoint(checkpoint, sig, results, done, props,
                             nacelle_acc, status, health_resid, health_cond)

    # the per-variant path reports the motion response only (AxRNA/props
    # stay NaN, same keys as the batched path)
    return _finalize()


def _clean_stale_tmp(checkpoint):
    """Remove orphaned ``{checkpoint}.<pid>.tmp.npz`` partials.

    A process killed mid-``_save_checkpoint`` leaves its tmp file
    behind; the rename protocol guarantees it is never the live
    checkpoint, so any survivor from another pid is garbage."""
    import glob
    import os

    for tmp in glob.glob(f"{checkpoint}.*.tmp.npz"):
        try:
            os.remove(tmp)
            _LOG.debug("removed stale checkpoint partial %s", tmp)
        except OSError as e:
            _LOG.debug("could not remove stale partial %s: %s", tmp, e)


def _save_checkpoint(checkpoint, sig, results, done, props, nacelle_acc,
                     status, health_resid, health_cond, mesh_shape=None,
                     chaos=None):
    import os

    if chaos is not None:
        chaos.maybe_raise("ckpt_fail")
    extra = {}
    if mesh_shape is not None:
        # recorded for post-mortem attribution only: resume is
        # deliberately topology-independent (per-design state carries no
        # shard identity, so a 1-device resume of an 8-device sweep — or
        # the reverse — picks up exactly where the checkpoint left off)
        extra["mesh_shape"] = np.asarray(mesh_shape, dtype=np.int64)
    # tmp + fsync + atomic rename: a kill at ANY point leaves either the
    # previous complete checkpoint or the new complete one — never a
    # truncated file (the .npz suffix keeps savez from renaming; writing
    # through the file object lets the bytes be fsynced before replace)
    tmp = f"{checkpoint}.{os.getpid()}.tmp.npz"
    with open(tmp, "wb") as fh:
        np.savez(fh, sig=sig, motion_std=results, done=done,
                 AxRNA_std=nacelle_acc, status=status,
                 health_resid=health_resid, health_cond=health_cond,
                 **extra, **props)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, checkpoint)
