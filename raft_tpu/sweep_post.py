"""Sweep postprocessing: reference-style contour figures over the grid.

The reference's parametersweep postprocessing (raft/parametersweep.py:
119-561) hand-writes a contourf panel for every pair of its five design
variables, metric by metric, with the remaining variables held at a
fixed index.  This module is the generic equivalent: given the factorial
sweep result and the axes definition, it reshapes each metric onto the
[n_1, ..., n_k] grid and emits one figure per metric containing every
ordered axis pair (x-axis variable sweeping, y-axis variable sweeping,
others at their middle value) — the same information layout as the
reference's 4x4 panels, for any number of axes.
"""

from __future__ import annotations

import os

import numpy as np


def _axis_label(path, i):
    if callable(path):
        name = getattr(path, "__name__", None)
        return name if name and name != "<lambda>" else f"axis {i}"
    return str(path).split(".")[-1] + f" [{path}]"


def _axis_scalars(values):
    """1-D scalar coordinate per axis value (contour axes need numbers);
    vector-valued axis entries (e.g. a diameter list) plot by their
    first element, falling back to the value index."""
    out = []
    for v in values:
        a = np.asarray(v, dtype=object)
        try:
            out.append(float(np.asarray(v, dtype=float).ravel()[0]))
        except (TypeError, ValueError):
            out.append(float(len(out)))
    return np.array(out)


def grid_metric(out, axes, metric):
    """Reshape a per-design metric onto the factorial grid.

    ``metric``: name of a 1-D [n_designs] entry in the sweep result, or
    an array.  Returns an array shaped [n_1, ..., n_k] following the
    axes order (itertools.product ordering, as ``sweep`` produces).
    """
    vals = out[metric] if isinstance(metric, str) else metric
    vals = np.asarray(vals)
    shape = tuple(len(v) for _, v in axes)
    return vals.reshape(shape + vals.shape[1:])


def plot_sweep_contours(out, axes, metrics=None, out_dir=".", prefix="sweep",
                        fixed_index=None):
    """Write one all-pairs contour figure per metric.

    Parameters
    ----------
    out : dict
        Result of :func:`raft_tpu.sweep.sweep` (needs per-design arrays;
        'motion_std' channels surge_std/.../yaw_std are derived
        automatically, plus any of mass/displacement/GMT present).
    axes : list of (path, values)
        The axes the sweep ran with.
    metrics : list of str, optional
        Which metrics to plot; default = everything available.
    fixed_index : list of int, optional
        Index each non-plotted axis is held at (default: middle).

    Returns the list of written figure paths.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    n_axes = len(axes)
    if n_axes < 2:
        raise ValueError("contour postprocessing needs at least two sweep axes")
    coords = [_axis_scalars(v) for _, v in axes]
    labels = [_axis_label(p, i) for i, (p, _) in enumerate(axes)]
    if fixed_index is None:
        fixed_index = [len(v) // 2 for _, v in axes]

    # assemble available per-design metrics
    fields = {}
    ms = np.asarray(out["motion_std"])  # [nd, ncase, 6]
    # unhealthy designs (non-converged/ill-conditioned/nan/quarantined;
    # see raft_tpu.robust.health) plot as holes, not as plausible-looking
    # garbage contours
    bad = None
    if "status" in out:
        bad = np.asarray(out["status"]) != 0
        if bad.any():
            ms = np.where(bad[:, None, None], np.nan, ms)
    dof = ["surge", "sway", "heave", "roll", "pitch", "yaw"]
    worst = ms.max(axis=1)  # worst sea state per design
    for i, name in enumerate(dof):
        fields[f"{name}_std"] = worst[:, i]
    for key in ("mass", "displacement", "GMT"):
        if key in out:
            vals = np.asarray(out[key])
            if bad is not None and bad.any():
                vals = np.where(bad, np.nan, vals)
            fields[key] = vals
    if metrics is not None:
        fields = {k: fields[k] for k in metrics}

    paths = []
    for name, vals in fields.items():
        G = grid_metric(out, axes, vals)
        fig, ax = plt.subplots(n_axes, n_axes,
                               figsize=(4.5 * n_axes, 3.8 * n_axes),
                               squeeze=False)
        for iy in range(n_axes):
            for ix in range(n_axes):
                a = ax[iy][ix]
                if ix == iy:
                    # diagonal: 1-D cut along this axis
                    idx = list(fixed_index)
                    idx[ix] = slice(None)
                    a.plot(coords[ix], G[tuple(idx)], "o-")
                    a.set_xlabel(labels[ix])
                    a.set_ylabel(name)
                    continue
                if len(coords[ix]) < 2 or len(coords[iy]) < 2:
                    # contourf needs a 2x2 field; a single-value axis
                    # degenerates this panel to the diagonal's 1-D cut
                    one = ix if len(coords[ix]) >= 2 else iy
                    idx = list(fixed_index)
                    idx[one] = slice(None)
                    if len(coords[one]) >= 2:
                        a.plot(coords[one], G[tuple(idx)], "o-")
                    a.set_xlabel(labels[one])
                    a.set_ylabel(name)
                    continue
                idx = list(fixed_index)
                idx[ix] = slice(None)
                idx[iy] = slice(None)
                F = G[tuple(idx)]
                # F dims follow axis order; put iy on rows, ix on cols
                if ix < iy:
                    F = F.T
                X, Y = np.meshgrid(coords[ix], coords[iy])
                cf = a.contourf(X, Y, F)
                fig.colorbar(cf, ax=a, label=name)
                a.set_xlabel(labels[ix])
                a.set_ylabel(labels[iy])
        fig.suptitle(f"{name} over the design sweep "
                     f"(other axes at index {fixed_index})")
        fig.tight_layout()
        path = os.path.join(out_dir, f"{prefix}_{name}.png")
        fig.savefig(path, dpi=110)
        plt.close(fig)
        paths.append(path)
    return paths
