"""BEM-tier attestation: run a potential-flow (potMod) design sweep in
THIS process — single-device, warm repeat, and on a virtual-device mesh
— and assert, from numpy, the warnings machinery and the run ledger,
the batched BEM tier's contract:

- the potMod sweep runs the BATCHED path natively: no SweepAxisError
  fallback, no dropped-coefficient ``capability_fallback``, finite
  converged responses;
- the warm repeat reuses the memoized BEM coefficients (ledger
  ``bem_precompute`` with ``cache: "hit"``), performs ZERO real XLA
  compiles, and is bit-identical to the first run;
- the mesh sweep agrees with the single-device sweep (the BEM leaves
  are host-precomputed numpy, identical per shard, so the mesh
  bit-identity contract extends to potential-flow sweeps);
- ``RAFT_TPU_BEM=off`` restores the degraded path: a DROPS warning, a
  ``capability_fallback`` ledger event, and measurably different
  physics (the BEM contributions are really in the answers).

CI runs it on a forced virtual-device CPU mesh:

    python scripts/bem_check.py --devices 2 --ledger bem-ledgers
"""

import argparse
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _read_single_run(ledger_dir):
    from raft_tpu.obs import ledger as obs_ledger

    runs = obs_ledger.list_runs(ledger_dir)
    assert len(runs) == 1, f"expected one ledger run in {ledger_dir}: {runs}"
    return obs_ledger.read_events(runs[0])


def _events_by_name(ledger_dir):
    by = {}
    for ev in _read_single_run(ledger_dir):
        by.setdefault(ev["event"], []).append(ev)
    return by


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=2,
                    help="virtual CPU mesh size (default 2)")
    ap.add_argument("--ledger", default="bem-ledgers",
                    help="parent dir for the per-run ledgers")
    args = ap.parse_args()

    from raft_tpu import config as _config

    _config.force_host_mesh(args.devices)

    import numpy as np
    import jax

    from raft_tpu.analysis.recompile import RecompileSentinel
    from raft_tpu.designs import demo_spar
    from raft_tpu.sweep import sweep

    devs = jax.devices()
    assert len(devs) >= args.devices, (
        f"need {args.devices} devices, have {len(devs)}")
    devs = devs[:args.devices]

    design = demo_spar(nw_freqs=(0.05, 0.4))
    design["platform"]["potModMaster"] = 0
    design["platform"]["members"][0]["potMod"] = True

    base = np.array([9.4, 9.4, 6.5, 6.5])
    axes = [("platform.members.0.d",
             [(base + 0.2 * i).tolist() for i in range(2 * args.devices)])]
    # one state carries a nonzero wave heading: the solved heading set
    # must cover it exactly (heading-union contract)
    states = [(4.0, 8.0), (6.0, 10.0, 30.0)]
    kw = dict(n_iter=8, chunk_size=2)

    def run(tag, **extra):
        os.environ["RAFT_TPU_LEDGER"] = os.path.join(args.ledger, tag)
        try:
            return sweep(design, axes, states, **kw, **extra)
        finally:
            del os.environ["RAFT_TPU_LEDGER"]

    # ---- native potMod sweep: no fallback, no dropped coefficients ----
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any DROPS warning fails hard
        single = run("single", device=devs[0])
    assert np.all(np.asarray(single["status"]) == 0), single["status"]
    assert np.all(np.isfinite(single["motion_std"])), "non-finite output"
    by = _events_by_name(os.path.join(args.ledger, "single"))
    assert "capability_fallback" not in by, by["capability_fallback"]
    pre = by.get("bem_precompute")
    assert pre and pre[0]["cache"] == "miss", pre

    # ---- warm repeat: memoized BEM + zero real XLA compiles -----------
    with RecompileSentinel() as s:
        warm = run("warm", device=devs[0])
    assert s.backend_compiles == 0, (
        f"warm potMod sweep performed {s.backend_compiles} real XLA "
        f"compiles: {dict(s.compiles_by_name)}")
    by = _events_by_name(os.path.join(args.ledger, "warm"))
    pre = by.get("bem_precompute")
    assert pre and pre[0]["cache"] == "hit", pre

    # ---- mesh run: the tier composes with the sharded executor --------
    mesh = run("mesh", devices=devs)

    for out, tag in ((warm, "warm"), (mesh, "mesh")):
        for k in ("motion_std", "AxRNA_std", "mass", "displacement",
                  "GMT", "status"):
            a, b = np.asarray(single[k]), np.asarray(out[k])
            assert a.dtype == b.dtype, (tag, k, a.dtype, b.dtype)
            np.testing.assert_array_equal(a, b, err_msg=f"{tag}:{k}")

    # ---- BEM off: the degraded path still exists, and differs ---------
    os.environ["RAFT_TPU_BEM"] = "off"
    try:
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            off = run("off")
    finally:
        del os.environ["RAFT_TPU_BEM"]
    assert any("DROPS" in str(w.message) for w in rec), (
        "BEM-off potMod sweep did not warn about dropped coefficients")
    by = _events_by_name(os.path.join(args.ledger, "off"))
    assert "capability_fallback" in by, sorted(by)
    delta = np.nanmax(np.abs(np.asarray(single["motion_std"])
                             - np.asarray(off["motion_std"])))
    assert delta > 1e-6, (
        f"BEM on/off motion_std identical (max delta {delta}) — the tier "
        "contributed nothing")

    print(f"bem_check OK: {len(axes[0][1])} potMod designs x {len(states)} "
          f"cases — native batched BEM (no fallback), warm repeat 0 XLA "
          f"compiles + memoized coefficients, bit-identical on a "
          f"{args.devices}-device mesh, BEM-off delta {delta:.3e}")


if __name__ == "__main__":
    main()
