"""Chaos attestation: inject each fault class into the demo sweep in
THIS process and assert, from numpy and the run ledger, the robustness
layer's contract:

- every injected fault ends in a COMPLETED sweep that is BIT-IDENTICAL
  to the clean baseline (every result array, dtype included — health
  and status too), or in a typed, resumable preemption;
- a hung chunk trips the watchdog deadline (``chunk_timeout`` in the
  ledger) and the quarantine retry recovers it;
- losing a device mid-sweep re-meshes onto the survivors
  (``device_lost`` + ``remesh`` events) and resumes to completion;
- the post-remesh topology repeats warm with ZERO real XLA compiles
  (RecompileSentinel and the ledger both attest);
- a SIGTERM delivered at a chunk boundary drains, flushes the
  checkpoint, exits typed (``run_end ok=false reason=preempted``), and
  the resume is bit-identical with zero extra compiles.

CI runs it on an 8-virtual-device CPU mesh and gates the post-remesh
warm ledger with `obs.history check --require "real_compiles<=0"`:

    python scripts/chaos_check.py --devices 8 --ledger chaos-ledgers
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _events(ledger_dir):
    from raft_tpu.obs import ledger as obs_ledger

    runs = obs_ledger.list_runs(ledger_dir)
    assert len(runs) == 1, f"expected one ledger run in {ledger_dir}: {runs}"
    return obs_ledger.read_events(runs[0])


def _by_type(events):
    by = {}
    for ev in events:
        by.setdefault(ev["event"], []).append(ev)
    return by


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual CPU mesh size (default 8)")
    ap.add_argument("--ledger", default="chaos-ledgers",
                    help="parent dir for the per-scenario run ledgers")
    args = ap.parse_args()

    from raft_tpu import config as _config

    _config.force_host_mesh(args.devices)

    import numpy as np
    import jax

    from raft_tpu.analysis.recompile import RecompileSentinel
    from raft_tpu.designs import demo_spar
    from raft_tpu.robust.elastic import SweepPreempted
    from raft_tpu.sweep import sweep

    devs = jax.devices()
    assert len(devs) >= args.devices, (
        f"need {args.devices} devices, have {len(devs)}")

    design = demo_spar(nw_freqs=(0.05, 0.4))
    base_d = np.array([9.4, 9.4, 6.5, 6.5])
    axes = [("platform.members.0.d",
             [(base_d + 0.05 * i).tolist() for i in range(8)])]
    states = [(4.0, 8.0), (6.0, 10.0)]
    kw = dict(n_iter=8, chunk_size=2)

    def run(tag, **extra):
        os.environ["RAFT_TPU_LEDGER"] = os.path.join(args.ledger, tag)
        try:
            return sweep(design, axes, states, **kw, **extra)
        finally:
            del os.environ["RAFT_TPU_LEDGER"]

    def assert_identical(out, tag):
        for k in ("motion_std", "AxRNA_std", "mass", "displacement",
                  "GMT", "status"):
            a, b = np.asarray(baseline[k]), np.asarray(out[k])
            assert a.dtype == b.dtype, (tag, k, a.dtype, b.dtype)
            np.testing.assert_array_equal(a, b, err_msg=f"{tag}:{k}")
        for k in baseline["health"]:
            np.testing.assert_array_equal(
                np.asarray(baseline["health"][k]),
                np.asarray(out["health"][k]), err_msg=f"{tag}:health.{k}")

    baseline = run("baseline", device=devs[0])
    assert np.all(np.isfinite(baseline["motion_std"])), "non-finite baseline"

    # ---- 1. hung chunk -> watchdog deadline -> quarantine recovery ----
    os.environ.update({"RAFT_TPU_WATCHDOG": "1",
                       "RAFT_TPU_WATCHDOG_FLOOR": "0.5",
                       "RAFT_TPU_WATCHDOG_COLD": "5.0"})
    try:
        out = run("timeout", device=devs[0], chaos="hang:chunk=1,secs=60")
    finally:
        for var in ("RAFT_TPU_WATCHDOG", "RAFT_TPU_WATCHDOG_FLOOR",
                    "RAFT_TPU_WATCHDOG_COLD"):
            del os.environ[var]
    assert_identical(out, "timeout")
    by = _by_type(_events(os.path.join(args.ledger, "timeout")))
    assert by.get("chunk_timeout"), "watchdog never tripped"
    assert by["run_end"][0]["ok"] is True, by["run_end"]

    # ---- 2. device loss mid-sweep -> elastic re-mesh ------------------
    half = devs[:args.devices // 2]
    lost_id = int(half[-1].id)
    out = run("remesh", devices=half,
              chaos=f"device_lost:chunk=0,device={lost_id}")
    assert_identical(out, "remesh")
    by = _by_type(_events(os.path.join(args.ledger, "remesh")))
    assert by.get("device_lost"), "device loss never surfaced"
    remesh = by["remesh"][0]
    assert lost_id in remesh["from_devices"], remesh
    assert lost_id not in remesh["to_devices"], remesh
    assert len(remesh["to_devices"]) == len(half) - 1, remesh
    assert by["run_end"][0]["ok"] is True, by["run_end"]

    # ---- 3. post-remesh topology repeats warm, zero XLA compiles ------
    survivors = [d for d in half if int(d.id) != lost_id]
    with RecompileSentinel() as s:
        out = run("remesh-warm", devices=survivors)
    assert s.backend_compiles == 0, (
        f"post-remesh warm sweep performed {s.backend_compiles} real XLA "
        f"compiles: {dict(s.compiles_by_name)}")
    assert_identical(out, "remesh-warm")
    by = _by_type(_events(os.path.join(args.ledger, "remesh-warm")))
    warm_compiles = [e for e in by.get("compile_start", ()) if e.get("real")]
    assert not warm_compiles, (
        f"post-remesh warm ledger recorded real compiles: {warm_compiles}")

    # ---- 4. SIGTERM at a chunk boundary -> drain -> resume ------------
    ckpt = os.path.join(args.ledger, "preempt.npz")
    try:
        run("preempt", device=devs[0], checkpoint=ckpt,
            chaos="preempt:chunk=1")
        raise AssertionError("preempt chaos did not interrupt the sweep")
    except SweepPreempted as e:
        print(f"preempted as intended: {e}")
    by = _by_type(_events(os.path.join(args.ledger, "preempt")))
    assert by.get("preempt"), "no preempt event in the ledger"
    end = by["run_end"][0]
    assert end["ok"] is False and end.get("reason") == "preempted", end
    with np.load(ckpt, allow_pickle=False) as dat:
        n_done = int(dat["done"].sum())
    assert 0 < n_done < len(axes[0][1]), (
        f"preempt checkpoint holds {n_done} designs — not a mid-sweep drain")

    with RecompileSentinel() as s:
        out = run("resume", device=devs[0], checkpoint=ckpt)
    assert s.backend_compiles == 0, (
        f"resume performed {s.backend_compiles} real XLA compiles")
    assert_identical(out, "resume")

    print(f"chaos_check OK: {len(axes[0][1])} designs x {len(states)} cases "
          f"— watchdog timeout recovered, {len(half)}->{len(survivors)} "
          f"device re-mesh bit-identical (warm repeat 0 XLA compiles), "
          f"SIGTERM drain left {n_done} designs checkpointed and the "
          f"resume matched the baseline bit-for-bit")


if __name__ == "__main__":
    main()
