"""Cold-start attestation: run the demo sweep in THIS process against a
serialized-executable cache and assert, from the run ledger, how the
executables were obtained.

CI runs this twice in SEPARATE processes sharing RAFT_TPU_EXEC_CACHE:

    python scripts/coldstart_check.py --expect cold --ledger ledger-cold
    python scripts/coldstart_check.py --expect warm --ledger ledger-warm

The first process compiles for real and serializes the executables; the
second must obtain every executable from the cache — only
exec_cache_hit events, no compile_start with real=true — while
producing finite results.  Process separation is the point: nothing
in-memory (template memo, jax jit caches) can leak between the runs.
"""

import argparse
import os
import sys

# invoked as `python scripts/coldstart_check.py` — put the repo root on
# the path so raft_tpu imports regardless of the caller's cwd
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--expect", choices=["cold", "warm"], required=True)
    ap.add_argument("--ledger", required=True,
                    help="run-ledger directory for this process")
    ap.add_argument("--cache", default=None,
                    help="exec cache dir (default: $RAFT_TPU_EXEC_CACHE)")
    args = ap.parse_args()

    if args.cache:
        os.environ["RAFT_TPU_EXEC_CACHE"] = args.cache
    if not os.environ.get("RAFT_TPU_EXEC_CACHE"):
        ap.error("--cache or RAFT_TPU_EXEC_CACHE is required")
    os.environ["RAFT_TPU_LEDGER"] = args.ledger

    import numpy as np

    from raft_tpu.designs import demo_spar
    from raft_tpu.obs import ledger as obs_ledger
    from raft_tpu.sweep import sweep

    axes = [("platform.members.0.d",
             [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
              [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5]])]
    out = sweep(demo_spar(nw_freqs=(0.05, 0.4)), axes,
                [(4.0, 8.0), (6.0, 10.0)], n_iter=8, chunk_size=2)
    assert np.all(np.isfinite(out["motion_std"])), "non-finite sweep output"

    runs = obs_ledger.list_runs(args.ledger)
    assert len(runs) == 1, f"expected one ledger run, found {runs}"
    events = obs_ledger.read_events(runs[0])
    by = {}
    for ev in events:
        by.setdefault(ev["event"], []).append(ev)

    real_compiles = [e for e in by.get("compile_start", ())
                     if e.get("real")]
    hits = {e["key"] for e in by.get("exec_cache_hit", ())}
    stores = {e["key"] for e in by.get("exec_cache_store", ())}
    rejects = by.get("exec_cache_reject", ())

    if args.expect == "cold":
        assert real_compiles, "cold run performed no real XLA compiles"
        assert stores == {"A", "B"}, (
            f"cold run serialized {sorted(stores)}, expected A and B")
    else:
        assert not rejects, f"warm run rejected cache entries: {rejects}"
        assert not real_compiles, (
            "warm run performed REAL XLA compiles — the serialized "
            f"executable cache did not carry across processes: {real_compiles}")
        assert hits == {"A", "B"}, (
            f"warm run deserialized {sorted(hits)}, expected A and B")
        bad = [e for e in by.get("compile_end", ())
               if e["cache"] != "exec_cache" or e.get("xla_compiles", 0)]
        assert not bad, f"warm run compile_end not from exec cache: {bad}"

    n = {k: len(v) for k, v in by.items() if k.startswith(("compile", "exec"))}
    print(f"coldstart_check --expect {args.expect}: OK ({n})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
