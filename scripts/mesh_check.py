"""Mesh-executor attestation: run the demo sweep single-device and on
the full virtual-device mesh in THIS process and assert, from numpy and
the run ledger, the mesh executor's contract:

- the mesh sweep is BIT-IDENTICAL to the single-device sweep (every
  result array, dtype included — health and status too);
- the warm mesh repeat performs ZERO real XLA compiles (the chunk
  executables are memoized per mesh topology; RecompileSentinel and the
  ledger both attest);
- every shard carried real rows (the per-device d2h split in the
  ledger's chunk_fetch events names each device).

CI runs it on an 8-virtual-device CPU mesh and gates the warm ledger
with `obs.history check --require "real_compiles<=0"`:

    python scripts/mesh_check.py --devices 8 --ledger mesh-ledgers
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _read_single_run(ledger_dir):
    from raft_tpu.obs import ledger as obs_ledger

    runs = obs_ledger.list_runs(ledger_dir)
    assert len(runs) == 1, f"expected one ledger run in {ledger_dir}: {runs}"
    return obs_ledger.read_events(runs[0])


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual CPU mesh size (default 8)")
    ap.add_argument("--ledger", default="mesh-ledgers",
                    help="parent dir for the single/cold/warm run ledgers")
    args = ap.parse_args()

    from raft_tpu import config as _config

    _config.force_host_mesh(args.devices)

    import numpy as np
    import jax

    from raft_tpu.analysis.recompile import RecompileSentinel
    from raft_tpu.designs import demo_spar
    from raft_tpu.sweep import sweep

    devs = jax.devices()
    assert len(devs) >= args.devices, (
        f"need {args.devices} devices, have {len(devs)}")
    devs = devs[:args.devices]

    design = demo_spar(nw_freqs=(0.05, 0.4))
    base = np.array([9.4, 9.4, 6.5, 6.5])
    axes = [("platform.members.0.d",
             [(base + 0.05 * i).tolist() for i in range(2 * args.devices)])]
    states = [(4.0, 8.0), (6.0, 10.0)]
    # chunk 2 x 2*devices designs fills every shard with real rows
    kw = dict(n_iter=8, chunk_size=2)

    def run(tag, **extra):
        os.environ["RAFT_TPU_LEDGER"] = os.path.join(args.ledger, tag)
        try:
            return sweep(design, axes, states, **kw, **extra)
        finally:
            del os.environ["RAFT_TPU_LEDGER"]

    single = run("single", device=devs[0])
    cold = run("mesh-cold", devices=devs)
    with RecompileSentinel() as s:
        warm = run("mesh-warm", devices=devs)
    assert s.backend_compiles == 0, (
        f"warm mesh sweep performed {s.backend_compiles} real XLA "
        f"compiles: {dict(s.compiles_by_name)}")

    # ---- bit-identity: every array, dtype included --------------------
    for out, tag in ((cold, "cold"), (warm, "warm")):
        for k in ("motion_std", "AxRNA_std", "mass", "displacement",
                  "GMT", "status"):
            a, b = np.asarray(single[k]), np.asarray(out[k])
            assert a.dtype == b.dtype, (tag, k, a.dtype, b.dtype)
            np.testing.assert_array_equal(a, b, err_msg=f"{tag}:{k}")
        for k in single["health"]:
            np.testing.assert_array_equal(
                np.asarray(single["health"][k]),
                np.asarray(out["health"][k]), err_msg=f"{tag}:health.{k}")
    assert np.all(np.isfinite(single["motion_std"])), "non-finite output"

    # ---- ledger: the mesh plan + per-shard d2h actually happened ------
    events = _read_single_run(os.path.join(args.ledger, "mesh-warm"))
    by = {}
    for ev in events:
        by.setdefault(ev["event"], []).append(ev)
    plan = by["plan"][0]
    assert plan["mesh"][0] == args.devices, plan
    assert len(plan["devices"]) == args.devices, plan
    fetches = by["chunk_fetch"]
    shards = set()
    for ev in fetches:
        shards.update((ev.get("per_device") or {}).keys())
    assert len(shards) == args.devices, (
        f"d2h split names {sorted(shards)}, expected {args.devices} shards")
    warm_compiles = [e for e in by.get("compile_start", ())
                     if e.get("real")]
    assert not warm_compiles, (
        f"warm mesh ledger recorded real compiles: {warm_compiles}")

    print(f"mesh_check OK: {len(axes[0][1])} designs x {len(states)} cases "
          f"on a {plan['mesh'][0]}x{plan['mesh'][1]} (design,case) mesh — "
          f"bit-identical to single-device, warm repeat 0 XLA compiles, "
          f"{len(shards)} shards fetched")


if __name__ == "__main__":
    main()
