"""Serve attestation: synthetic many-tenant load against the resident
solve server in THIS process, asserting the coalescing + robustness
contract from numpy, the run ledger, and the live stats:

- >= 8 concurrent mixed-size requests (4 tenants) coalesce into shared
  rounds — the ledger proves FEWER rounds than requests AND fewer chunk
  dispatches than requests;
- every surviving request's rows are BIT-IDENTICAL (dtypes included) to
  an individual ``sweep()`` call over that request's designs at the
  served chunk extent;
- the whole load phase runs with ZERO real XLA compiles after the
  bucket warm-up (RecompileSentinel attests in-process; CI re-asserts
  real_compiles<=0 from the load rounds' ledgers);
- one request is cancelled mid-queue and one carries an
  already-hopeless deadline: each fails TYPED, and only them;
- a device-loss fault injected into a round re-meshes inside the sweep
  and the round's requests still deliver, bit-identical — no request
  fails;
- sustained requests/s and p50/p99 latency are reported and written as
  a bench-style record for the history store's ``serve_p99_s`` gate.

CI runs it on an 8-virtual-device CPU mesh:

    python scripts/serve_check.py --devices 8 --ledger serve-ledgers
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _runs_in(ledger_dir):
    from raft_tpu.obs import ledger as obs_ledger

    return obs_ledger.list_runs(ledger_dir)


def _events_by_type(paths):
    from raft_tpu.obs import ledger as obs_ledger

    by = {}
    for path in paths:
        for ev in obs_ledger.read_events(path):
            by.setdefault(ev["event"], []).append(ev)
    return by


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual CPU mesh size (default 8)")
    ap.add_argument("--ledger", default="serve-ledgers",
                    help="parent dir for the per-phase run ledgers")
    ap.add_argument("--bench-out", default="serve-bench.json",
                    help="bench-style JSON record for the history store")
    args = ap.parse_args()

    from raft_tpu import config as _config

    _config.force_host_mesh(args.devices)

    import numpy as np
    import jax

    from raft_tpu.analysis.recompile import RecompileSentinel
    from raft_tpu.designs import demo_spar
    from raft_tpu.robust import STATUS_OK
    from raft_tpu.serve import (DeadlineExceeded, RequestCancelled,
                                SolveServer)
    from raft_tpu.sweep import sweep

    devs = jax.devices()
    assert len(devs) >= args.devices, (
        f"need {args.devices} devices, have {len(devs)}")

    design = demo_spar(nw_freqs=(0.05, 0.4))
    base_d = np.array([9.4, 9.4, 6.5, 6.5])
    variants = [(base_d + 0.05 * i).tolist() for i in range(8)]
    axes = [("platform.members.0.d", variants)]
    states = [(4.0, 8.0), (6.0, 10.0)]
    n_iter = 8
    chunk_size = 4

    def pt(i):
        return (variants[i % len(variants)],)

    def ledger_to(tag):
        os.environ["RAFT_TPU_LEDGER"] = os.path.join(args.ledger, tag)

    result_keys = ("motion_std", "AxRNA_std", "mass", "displacement",
                   "GMT", "status")

    def assert_identical(direct, got, tag, n):
        for k in result_keys:
            a = np.asarray(direct[k])[:n]
            b = np.asarray(got[k])[:n]
            assert a.dtype == b.dtype, (tag, k, a.dtype, b.dtype)
            np.testing.assert_array_equal(a, b, err_msg=f"{tag}:{k}")
        for k in direct["health"]:
            np.testing.assert_array_equal(
                np.asarray(direct["health"][k])[:n],
                np.asarray(got["health"][k])[:n],
                err_msg=f"{tag}:health.{k}")

    # ---- resident server: construct + bucket warm-up -------------------
    ledger_to("serve")
    srv = SolveServer(
        design, axes, states, n_iter=n_iter, devices=devs[:args.devices],
        config={"chunk_size": chunk_size, "max_round_designs": 16,
                "max_pending_designs": 64, "max_request_designs": 6,
                "retry_rounds": 1,
                "drain_path": os.path.join(args.ledger, "drain.json")})
    ledger_to("warm")
    t0 = time.perf_counter()
    srv.start(warm="buckets")
    warm_s = time.perf_counter() - t0

    # mixed-size request grids for 4 tenants; >= 8 concurrent requests
    request_grids = [
        [pt(0), pt(1), pt(2), pt(3)],
        [pt(4), pt(5)],
        [pt(6)],
        [pt(7), pt(0), pt(1)],
        [pt(2), pt(3)],
        [pt(4)],
        [pt(5), pt(6), pt(7), pt(0)],
        [pt(1), pt(2)],
    ]

    # individual-sweep baselines at the served chunk extent (requests
    # smaller than one chunk are padded by row repetition — rows are
    # vmap-independent, so the request's rows are untouched; this also
    # keeps the baseline at the same extent the server pins)
    baselines = []
    for grid in request_grids:
        padded = grid + [grid[0]] * max(0, chunk_size - len(grid))
        baselines.append(sweep(design, axes, states, n_iter=n_iter,
                               chunk_size=chunk_size, grid=padded))

    # ---- load phase: concurrent submit + cancel + dead deadline --------
    ledger_to("load")
    accepted0 = srv.stats()["accepted"]
    rounds0 = srv.stats()["rounds"]
    tickets = [None] * len(request_grids)

    def submit(i):
        tickets[i] = srv.submit(request_grids[i], tenant=f"tenant{i % 4}")

    with RecompileSentinel() as sentinel:
        t0 = time.perf_counter()
        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(len(request_grids))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # submitted while round 1 is in flight: still queued, so the
        # cancel lands pre-dispatch and the dead deadline expires at
        # composition — each fails typed, nobody else notices
        victim = srv.submit([pt(3)], tenant="tenant-cancel")
        hopeless = srv.submit([pt(5)], tenant="tenant-late",
                              deadline_s=0.05)
        assert victim.cancel() is True, "cancel landed after delivery"
        results = [t.result(timeout=900) for t in tickets]
        load_s = time.perf_counter() - t0
        compiles = sentinel.backend_compiles
    assert compiles == 0, (
        f"load phase performed {compiles} real XLA compiles after "
        f"warm-up: {dict(sentinel.compiles_by_name)}")

    try:
        victim.result(timeout=10)
        raise AssertionError("cancelled request delivered results")
    except RequestCancelled:
        pass
    try:
        hopeless.result(timeout=10)
        raise AssertionError("past-deadline request delivered results")
    except DeadlineExceeded:
        pass

    for i, (grid, got) in enumerate(zip(request_grids, results)):
        assert list(got["grid"]) == grid, f"request {i} row routing"
        assert (np.asarray(got["status"]) == STATUS_OK).all(), (
            f"request {i} status {got['status']}")
        assert_identical(baselines[i], got, f"request{i}", len(grid))

    st = srv.stats()
    n_requests = st["accepted"] - accepted0          # incl. victim+hopeless
    n_rounds = st["rounds"] - rounds0
    assert n_requests == len(request_grids) + 2, (n_requests, st)
    assert n_rounds < len(request_grids), (
        f"no coalescing: {n_rounds} rounds for {len(request_grids)} "
        f"delivered requests")
    by = _events_by_type(_runs_in(os.path.join(args.ledger, "load")))
    n_chunks = len(by.get("chunk_dispatch", ()))
    assert 0 < n_chunks < len(request_grids), (
        f"expected fewer chunk dispatches than the {len(request_grids)} "
        f"coalesced requests, ledger shows {n_chunks}")
    real = [e for e in by.get("compile_start", ()) if e.get("real")]
    assert not real, f"load rounds recorded real compiles: {real}"

    # ---- chaos phase: device loss mid-round, nobody fails --------------
    # the mesh design axis is sized to the workload (ceil(designs /
    # chunk)), so the round must span >= 2 chunks for a second device to
    # participate at all; both submits happen under the server lock so
    # they provably coalesce into ONE 8-design round across devices
    # [0, 1], and the injected loss targets a participating device
    ledger_to("chaos")
    lost_id = int(devs[1].id)
    srv.inject_chaos(f"device_lost:chunk=0,device={lost_id}")
    with srv._lock:
        ta = srv.submit(request_grids[0], tenant="tenant0")
        tb = srv.submit(request_grids[6], tenant="tenant1")
    ra, rb = ta.result(timeout=900), tb.result(timeout=900)
    del os.environ["RAFT_TPU_LEDGER"]
    assert_identical(baselines[0], ra, "chaos-a", 4)
    assert_identical(baselines[6], rb, "chaos-b", 4)
    by = _events_by_type(_runs_in(os.path.join(args.ledger, "chaos")))
    assert by.get("device_lost"), "injected device loss never surfaced"
    remesh = by["remesh"][0]
    assert lost_id in remesh["from_devices"], remesh
    assert lost_id not in remesh["to_devices"], remesh

    stats = srv.stats()
    srv.close()

    # ---- headline + history record -------------------------------------
    rps = (len(request_grids) + 2) / load_s
    record = {
        "metric": "serve_load_wall_s",
        "value": round(load_s, 3),
        "t": time.time(),
        "detail": {
            "devices": args.devices,
            "chunk_size": chunk_size,
            "warm_s": round(warm_s, 3),
            "serve_requests": n_requests,
            "serve_rounds": n_rounds,
            "serve_chunks": n_chunks,
            "serve_rps": round(rps, 3),
            "serve_p50_s": stats["p50_s"],
            "serve_p99_s": stats["p99_s"],
            "repeat_xla_compiles": compiles,
            "cancelled": stats["cancelled"],
            "deadline": stats["deadline"],
        },
    }
    with open(args.bench_out, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(record) + "\n")

    print(f"serve_check OK: {n_requests} requests from 6 tenants "
          f"coalesced into {n_rounds} rounds / {n_chunks} chunks on "
          f"{args.devices} devices — bit-identical to solo sweeps, "
          f"0 real XLA compiles after warm-up, cancel + deadline failed "
          f"typed, device-loss round re-meshed with no request lost; "
          f"sustained {rps:.2f} req/s, p50 {stats['p50_s']}s, "
          f"p99 {stats['p99_s']}s (warm-up {warm_s:.1f}s)")


if __name__ == "__main__":
    main()
