"""Test configuration: run JAX on a virtual 8-device CPU mesh in float64.

Multi-chip sharding is validated on the host platform via
``--xla_force_host_platform_device_count`` (no TPU pod is needed), and
float64 is enabled so results can be compared against the reference
golden values at rtol≈1e-5 (see /root/reference/tests/*).

Note: this environment pre-registers a TPU PJRT plugin in every Python
process (sitecustomize on PYTHONPATH) and latches JAX_PLATFORMS at that
import, so the platform must be forced back to ``cpu`` through
``jax.config`` here — plain env vars are read too early to help.
"""

import os

from raft_tpu import config as _config

_config.force_host_mesh(8)
_config.enable_x64()

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _bound_xla_program_accumulation():
    """Clear JAX's compiled-program caches after every test module.

    A full-suite run accumulates thousands of XLA:CPU executables
    (eager primitives + per-topology jitted kernels); past a threshold
    the XLA:CPU compiler segfaults deterministically on this host
    (observed repeatedly at the same collection position, while every
    module passes standalone).  Bounding the live program count per
    module keeps the suite in the regime each module is validated in —
    at the cost of recompiling shared kernels per module.
    """
    yield
    import jax

    jax.clear_caches()


REFERENCE_DIR = "/root/reference"
REFERENCE_TEST_DATA = os.path.join(REFERENCE_DIR, "tests", "test_data")


@pytest.fixture(scope="session")
def ref_test_data():
    """Path to the reference implementation's golden test data, if present."""
    if not os.path.isdir(REFERENCE_TEST_DATA):
        pytest.skip("reference golden data not available")
    return REFERENCE_TEST_DATA
