"""Helpers to pull golden values out of the reference test corpus.

The reference's unit tests carry their expected values as inline numpy
literals (e.g. /root/reference/tests/test_member.py:51-357).  Rather than
duplicating hundreds of lines of numbers here, this module slices those
assignment statements out of the (read-only) reference test files and
evaluates just the literals.  Nothing else from the files is executed.
"""

from __future__ import annotations

import ast
import os

import numpy as np

REFERENCE_TESTS = "/root/reference/tests"


def load_literals(test_file: str, names: list[str]) -> dict:
    """Extract module-level ``name = <literal>`` assignments from a
    reference test file and evaluate them with numpy in scope."""
    path = os.path.join(REFERENCE_TESTS, test_file)
    with open(path) as f:
        tree = ast.parse(f.read())

    wanted = set(names)
    ns: dict = {"np": np}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and tgt.id in wanted:
                code = compile(ast.Expression(node.value), path, "eval")
                ns[tgt.id] = eval(code, {"np": np})  # noqa: S307 - literals only
    missing = wanted - ns.keys()
    if missing:
        raise KeyError(f"Could not find golden literals {missing} in {path}")
    return {k: ns[k] for k in names}
