"""Tests for raft_tpu.analysis: graftlint rules, shape contracts, and the
recompilation sentinel.

Each lint rule gets a positive (fires) and a negative (stays quiet) case;
the negatives encode the precision features (taint stops at .shape,
is-None tests, `# graftlint:` directives) that keep the linter usable on
the real package.
"""

import textwrap

import jax
import jax.numpy as jnp
import pytest

from raft_tpu.analysis import (
    RecompileSentinel,
    ShapeContractError,
    shape_contract,
    verify_contract,
)
from raft_tpu.analysis.graftlint import lint_source


def _rules(src, relpath="raft_tpu/ops/fake.py"):
    src = textwrap.dedent(src)
    return [v.rule for v in lint_source(src, relpath=relpath)]


# ---------------------------------------------------------------------------
# GL-NP-IN-JIT
# ---------------------------------------------------------------------------


def test_np_in_jit_fires():
    rules = _rules("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.clip(x, 0, 1)
        """)
    assert "GL-NP-IN-JIT" in rules


def test_np_on_host_constant_is_quiet():
    # np on untainted (host-side) values is fine inside a traced fn
    rules = _rules("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            bound = np.log(np.finfo(np.float32).max)
            return jnp.clip(x, -bound, bound)
        """)
    assert rules == []


def test_np_shape_query_on_tracer_is_quiet():
    # .shape/.ndim/len() of a tracer are static — not host syncs
    rules = _rules("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            n = x.shape[0]
            return jnp.zeros(np.maximum(n, 1)) + x
        """)
    assert rules == []


# ---------------------------------------------------------------------------
# GL-HOST-CAST
# ---------------------------------------------------------------------------


def test_host_cast_fires_on_float_and_item():
    rules = _rules("""
        import jax

        @jax.jit
        def f(x):
            a = float(x)
            b = x.sum().item()
            return a + b
        """)
    assert rules.count("GL-HOST-CAST") == 2


def test_host_cast_on_untainted_is_quiet():
    rules = _rules("""
        import jax
        import jax.numpy as jnp

        SCALE = "1.5"

        @jax.jit
        def f(x):
            return x * float(SCALE)
        """)
    assert rules == []


# ---------------------------------------------------------------------------
# GL-PY-BRANCH
# ---------------------------------------------------------------------------


def test_py_branch_fires_on_traced_if_and_while():
    rules = _rules("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                x = x * 2
            while x < 10:
                x = x + 1
            return x
        """)
    assert rules.count("GL-PY-BRANCH") == 2


def test_py_branch_quiet_on_none_shape_and_membership():
    rules = _rules("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x, opts, r6=None):
            if r6 is None:
                r6 = jnp.zeros(6)
            if "gain" in opts:
                x = x * opts["gain"]
            if x.shape[0] > 3:
                x = x[:3]
            return x + r6[:3]
        """)
    assert rules == []


def test_py_branch_respects_static_directive():
    src = """
        import jax

        @jax.jit
        def f(x, topo):  # graftlint: static=topo
            if topo.flag:
                return x * 2
            return x
        """
    assert _rules(src) == []
    assert "GL-PY-BRANCH" in _rules(src.replace("  # graftlint: static=topo", ""))


# ---------------------------------------------------------------------------
# GL-BARE-EXCEPT
# ---------------------------------------------------------------------------


def test_bare_except_fires():
    rules = _rules("""
        def f():
            try:
                risky()
            except Exception:
                pass
        """)
    assert "GL-BARE-EXCEPT" in rules


def test_handled_except_is_quiet():
    rules = _rules("""
        def f(log):
            try:
                risky()
            except Exception as e:
                log.append(e)
            try:
                risky()
            except ValueError:
                pass
        """)
    assert rules == []


# ---------------------------------------------------------------------------
# GL-STATIC-ARGS
# ---------------------------------------------------------------------------


def test_static_args_fires_on_array_value():
    rules = _rules("""
        import jax
        import numpy as np

        def g(x, idx):
            return x

        h = jax.jit(g, static_argnums=np.array([1]))
        """)
    assert "GL-STATIC-ARGS" in rules


def test_static_args_tuple_of_ints_is_quiet():
    rules = _rules("""
        import jax

        def g(x, n, tol=1e-3):
            return x * n

        h = jax.jit(g, static_argnums=(1,), static_argnames=("tol",))
        """)
    assert rules == []


# ---------------------------------------------------------------------------
# GL-F64-LITERAL (kernel dirs only)
# ---------------------------------------------------------------------------

_F64_SRC = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x.astype(jnp.float64)
    """


def test_f64_literal_fires_in_kernel_dir():
    assert "GL-F64-LITERAL" in _rules(_F64_SRC, relpath="raft_tpu/ops/fake.py")


def test_f64_literal_quiet_outside_kernel_dirs_and_when_gated():
    # non-kernel module: the widening is someone else's policy decision
    assert _rules(_F64_SRC, relpath="raft_tpu/core/fake.py") == []
    # dtype-conditional widen is the sanctioned pattern even in kernels
    rules = _rules("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x, x64):
            dt = jnp.complex128 if x64 else jnp.complex64
            return x.astype(dt)
        """, relpath="raft_tpu/ops/fake.py")
    assert "GL-F64-LITERAL" not in rules


# ---------------------------------------------------------------------------
# GL-PRINT
# ---------------------------------------------------------------------------

_PRINT_SRC = """
    def f(x, display=False):
        if display:
            print("progress", x)
        return x
    """


def test_print_fires_in_library_code():
    assert _rules(_PRINT_SRC, relpath="raft_tpu/core/fake.py") == ["GL-PRINT"]


def test_print_exempt_suffix_and_disable_directive():
    import textwrap as _tw

    from raft_tpu.analysis.graftlint import Config

    # CLI/report modules listed in [lint] print_exempt are skipped whole
    cfg = Config(print_exempt=("raft_tpu/obs/report.py",))
    vs = lint_source(_tw.dedent(_PRINT_SRC), cfg=cfg,
                     relpath="raft_tpu/obs/report.py")
    assert vs == []
    # ...but the same config still flags non-exempt files
    vs = lint_source(_tw.dedent(_PRINT_SRC), cfg=cfg,
                     relpath="raft_tpu/core/fake.py")
    assert [v.rule for v in vs] == ["GL-PRINT"]
    # per-line opt-out for the sanctioned funnel print
    rules = _rules("""
        def display_funnel(message):
            print(message)  # graftlint: disable=GL-PRINT
        """, relpath="raft_tpu/obs/fake_log.py")
    assert rules == []


# ---------------------------------------------------------------------------
# GL-NESTED-JIT
# ---------------------------------------------------------------------------


def test_nested_jit_fires():
    rules = _rules("""
        import jax

        @jax.jit
        def f(x):
            g = jax.jit(lambda y: y * 2)
            return g(x)
        """)
    assert "GL-NESTED-JIT" in rules


def test_module_level_jit_is_quiet():
    rules = _rules("""
        import jax

        def f(x):
            return x * 2

        f = jax.jit(f)
        """)
    assert rules == []


# ---------------------------------------------------------------------------
# trace reachability + directives
# ---------------------------------------------------------------------------


def test_reachability_through_vmap_and_closure():
    # helper isn't decorated, but it's called from a vmapped fn: traced
    rules = _rules("""
        import jax
        import numpy as np

        def helper(x):
            return np.abs(x)

        def outer(xs):
            return jax.vmap(lambda x: helper(x) * 2)(xs)
        """)
    assert "GL-NP-IN-JIT" in rules


def test_untraced_function_is_not_checked():
    rules = _rules("""
        import numpy as np

        def host_only(x):
            if x > 0:
                return float(np.clip(x, 0, 1))
            return 0.0
        """)
    assert rules == []


def test_disable_directive_suppresses():
    rules = _rules("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.clip(x, 0, 1)  # graftlint: disable=GL-NP-IN-JIT
        """)
    assert rules == []


def test_traced_directive_marks_root():
    src = """
        import numpy as np

        def f(x):{mark}
            return np.clip(x, 0, 1)
        """
    assert _rules(src.format(mark="")) == []
    assert "GL-NP-IN-JIT" in _rules(src.format(mark="  # graftlint: traced"))


def test_shape_contract_decorator_marks_root():
    rules = _rules("""
        import numpy as np
        from raft_tpu.analysis.contracts import shape_contract

        @shape_contract("[n]->[n]")
        def f(x):
            return np.clip(x, 0, 1)
        """)
    assert "GL-NP-IN-JIT" in rules


def test_baseline_ratchet_counts():
    from raft_tpu.analysis.graftlint import _baseline_counts

    vs = lint_source(textwrap.dedent("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            y = np.clip(x, 0, 1)
            return y + np.square(x)
        """), relpath="raft_tpu/ops/fake.py")
    counts = _baseline_counts(vs)
    assert counts == {"raft_tpu/ops/fake.py:GL-NP-IN-JIT": 2}


def test_repo_is_clean_against_baseline():
    """The shipped tree must lint clean (CI gate parity)."""
    import os

    from raft_tpu.analysis.graftlint import _baseline_counts, lint_paths, load_config

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_config(os.path.join(root, "graftlint.toml"))
    counts = _baseline_counts(
        lint_paths([os.path.join(root, "raft_tpu")], cfg=cfg, root=root))
    over = {k: (c, int(cfg.baseline.get(k, 0))) for k, c in counts.items()
            if c > int(cfg.baseline.get(k, 0))}
    assert not over, f"lint regressions vs graftlint.toml baseline: {over}"


def test_stale_baseline_entries_flagged_and_dropped(tmp_path, monkeypatch,
                                                   capsys):
    """A [baseline] entry whose file was renamed/deleted suppresses
    nothing and masks a future regression under the same key: plain runs
    must name it (without failing — the tree is still clean), and
    --update-baseline must drop it."""
    from raft_tpu.analysis.graftlint import load_config, main

    (tmp_path / "real.py").write_text(
        "def f(x):\n    print(x)\n    return x\n")
    cfg = tmp_path / "graftlint.toml"
    cfg.write_text('[baseline]\n"real.py:GL-PRINT" = 1\n'
                   '"gone.py:GL-PRINT" = 2\n')
    monkeypatch.chdir(tmp_path)

    rc = main(["real.py", "--config", str(cfg)])
    out = capsys.readouterr().out
    assert rc == 0
    assert ("gone.py:GL-PRINT: baselined file no longer exists" in out)
    # reported as stale, not double-reported as a loosened ratchet
    assert out.count("gone.py:GL-PRINT") == 1

    rc = main(["real.py", "--config", str(cfg), "--update-baseline"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 stale entr(y/ies) dropped" in out
    assert load_config(str(cfg)).baseline == {"real.py:GL-PRINT": 1}


# ---------------------------------------------------------------------------
# shape contracts
# ---------------------------------------------------------------------------


def test_contract_accepts_and_binds_dims():
    @shape_contract("[N,6],[6,nw]->[N,nw]")
    def apply(P, Xi):
        return P @ Xi

    out = apply(jnp.ones((4, 6)), jnp.ones((6, 10)))
    assert out.shape == (4, 10)


def test_contract_rejects_rank_and_literal_mismatch():
    @shape_contract("[N,6]->[N]")
    def rowsum(P):
        return P.sum(axis=-1)

    with pytest.raises(ShapeContractError, match="rank"):
        rowsum(jnp.ones((4,)))
    with pytest.raises(ShapeContractError, match="literal"):
        rowsum(jnp.ones((4, 5)))


def test_contract_rejects_inconsistent_dim_var():
    @shape_contract("[n],[n]->[n]")
    def add(a, b):
        return a + b

    with pytest.raises(ShapeContractError, match="rebinds"):
        add(jnp.ones(3), jnp.ones(4))


def test_contract_checks_outputs():
    @shape_contract("[n]->[n]")
    def bad(a):
        return jnp.concatenate([a, a])  # violates its own declaration

    with pytest.raises(ShapeContractError, match="output"):
        bad(jnp.ones(3))


def test_contract_skip_and_batch_dims():
    @shape_contract("_,[*,3]->[*,3,3]")
    def outer(params, v):
        return v[..., :, None] * v[..., None, :]

    assert outer({"any": "tree"}, jnp.ones((5, 2, 3))).shape == (5, 2, 3, 3)
    assert outer(None, jnp.ones(3)).shape == (3, 3)


def test_contract_works_under_jit_and_vmap():
    @shape_contract("[n],[n]->[n]")
    def add(a, b):
        return a + b

    jadd = jax.jit(add)
    assert jadd(jnp.ones(4), jnp.ones(4)).shape == (4,)
    with pytest.raises(ShapeContractError):
        jax.jit(add)(jnp.ones((2, 4)), jnp.ones((2, 4)))
    # under vmap the kernel sees unbatched shapes
    assert jax.vmap(add)(jnp.ones((7, 4)), jnp.ones((7, 4))).shape == (7, 4)


def test_contract_disable_env(monkeypatch):
    @shape_contract("[3]->[3]")
    def f(x):
        return x

    monkeypatch.setenv("RAFT_TPU_CONTRACTS", "0")
    assert f(jnp.ones(5)).shape == (5,)  # contract inert
    monkeypatch.setenv("RAFT_TPU_CONTRACTS", "1")
    with pytest.raises(ShapeContractError):
        f(jnp.ones(5))


def test_verify_contract_eval_shape():
    @shape_contract("[N,6],[6,nw]->[N,nw]")
    def apply(P, Xi):
        return P @ Xi

    out = verify_contract(apply, jax.ShapeDtypeStruct((4, 6), jnp.float32),
                          jax.ShapeDtypeStruct((6, 10), jnp.float32))
    assert out.shape == (4, 10)
    with pytest.raises(ShapeContractError):
        verify_contract(apply, jax.ShapeDtypeStruct((4, 5), jnp.float32),
                        jax.ShapeDtypeStruct((5, 10), jnp.float32))


def test_live_kernels_carry_contracts():
    """Acceptance: ≥10 shipped kernels are contract-decorated."""
    from raft_tpu.ops import transforms, waves
    from raft_tpu.parallel import smallsolve

    mods = [transforms, waves, smallsolve]
    decorated = [
        getattr(m, name) for m in mods for name in dir(m)
        if hasattr(getattr(m, name), "__shape_contract__")
    ]
    assert len(decorated) >= 10
    # and one of them verifies statically against production-like shapes
    from raft_tpu.ops.waves import kinematics_from_modes

    out = verify_contract(
        kinematics_from_modes,
        jax.ShapeDtypeStruct((12, 3), jnp.float64),
        jax.ShapeDtypeStruct((6, 40), jnp.complex128),
        jax.ShapeDtypeStruct((40,), jnp.float64))
    assert out[0].shape == (12, 3, 40)


# ---------------------------------------------------------------------------
# recompile sentinel
# ---------------------------------------------------------------------------


@pytest.mark.sentinel
def test_sentinel_counts_compiles_and_cache_hits():
    @jax.jit
    def f(x):
        return x * 2 + 1

    x = jnp.arange(8.0)
    # materialize the warm-call operand OUTSIDE the sentinel: the eager
    # `x + 1` is itself a tiny jit program and would count as a compile
    x1 = jax.block_until_ready(x + 1)
    with RecompileSentinel() as s:
        jax.block_until_ready(f(x))
        assert s.backend_compiles >= 1
        snap = s.snapshot()
        jax.block_until_ready(f(x1))  # same shape/dtype: cache hit
        s.assert_no_recompile(snap, "warm call")
        # a new shape is a legitimate second compile
        jax.block_until_ready(f(jnp.arange(16.0)))
        assert s.compiles_since(snap) >= 1


@pytest.mark.sentinel
def test_sentinel_detects_cache_key_churn():
    def make(scale):
        # fresh closure identity per call — the classic recompile bug
        return jax.jit(lambda x: x * scale)

    x = jnp.arange(8.0)
    with RecompileSentinel() as s:
        jax.block_until_ready(make(2.0)(x))
        snap = s.snapshot()
        jax.block_until_ready(make(2.0)(x))
        with pytest.raises(AssertionError, match="recompile"):
            s.assert_no_recompile(snap, "second wrapper")


@pytest.mark.sentinel
def test_sentinel_budget_and_nesting():
    @jax.jit
    def g(x):
        return x - 1

    with RecompileSentinel() as outer:
        with RecompileSentinel() as inner:
            jax.block_until_ready(g(jnp.arange(5.0)))
        assert inner.backend_compiles == outer.backend_compiles >= 1
        with pytest.raises(AssertionError, match="budget"):
            inner.assert_budget(0, "test")


@pytest.mark.sentinel
@pytest.mark.compile_budget(2)
def test_compile_budget_marker_enforced():
    @jax.jit
    def h(x):
        return x / 2

    jax.block_until_ready(h(jnp.arange(4.0)))
    jax.block_until_ready(h(jnp.arange(4.0)))  # warm: must not compile


@pytest.mark.sentinel
def test_production_kernel_hits_cache_on_second_call():
    """wave_number is jitted at module level: a second same-shape call
    must not compile anything."""
    from raft_tpu.ops import waves

    w = jnp.linspace(0.05, 2.0, 25)
    w2 = jax.block_until_ready(jnp.linspace(0.06, 2.01, 25))
    jax.block_until_ready(waves.wave_number(w, 180.0))
    with RecompileSentinel() as s:
        snap = s.snapshot()
        jax.block_until_ready(waves.wave_number(w2, 180.0))
        s.assert_no_recompile(snap, "warm wave_number")


# ---------------------------------------------------------------------------
# config behavior pinned by this PR
# ---------------------------------------------------------------------------


def test_compilation_cache_warns_on_cpu_with_explicit_path(tmp_path):
    """On the CPU backend the persistent cache is a documented no-op —
    but an explicitly requested path must warn, not vanish silently."""
    import warnings

    from raft_tpu.config import enable_compilation_cache

    assert jax.default_backend() == "cpu"
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = enable_compilation_cache(str(tmp_path / "cache"))
    assert out is None
    assert any("CPU backend" in str(w.message) for w in caught)

    # the implicit-path call stays silent (the common, intended case)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert enable_compilation_cache() is None
    assert caught == []
