"""Aux subsystem tests: IEC transient winds, sweep driver, OMDAO-style
headless compute, ballast trim, response export."""

import numpy as np
import pytest

from raft_tpu.rotor.wind import IECWindExtreme
from raft_tpu.designs import demo_spar


def test_iec_sigma_models():
    iec = IECWindExtreme()
    iec.Turbine_Class = "I"
    iec.Turbulence_Class = "B"
    iec.setup()
    assert iec.V_ref == 50.0 and iec.I_ref == 0.14
    # NTM at 10 m/s: 0.14*(7.5+5.6)
    assert np.isclose(iec.NTM(10.0), 0.14 * (0.75 * 10 + 5.6))
    sig, V_e50, V_e1, _, _ = iec.EWM(10.0)
    assert np.isclose(V_e50, 70.0) and np.isclose(V_e1, 56.0)


@pytest.mark.parametrize("event", ["EOG", "EDC", "ECD", "EWS"])
def test_iec_transients(event, tmp_path):
    iec = IECWindExtreme()
    iec.setup()
    t, cols = getattr(iec, event)(12.0)
    assert t[0] == 0.0 and len(t) > 100
    for k in ("V", "V_dir", "V_gust", "shear_vert"):
        assert len(cols[k]) == len(t)
        assert np.all(np.isfinite(cols[k]))
    if event == "EOG":
        assert cols["V_gust"].min() < -0.1  # gust dips
    if event == "EDC":
        assert abs(cols["V_dir"][-1]) > 5  # ends at full direction change
    path = iec.write_wnd(str(tmp_path / "x.wnd"), t, cols)
    assert len(open(path).readlines()) == len(t) + 3


def test_sweep_driver():
    from raft_tpu.sweep import sweep

    design = demo_spar(nw_freqs=(0.05, 0.4))
    out = sweep(
        design,
        axes=[("platform.members.0.d", [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5]])],
        sea_states=[(4.0, 8.0), (6.0, 10.0)],
        n_iter=8,
    )
    assert len(out["grid"]) == 2
    assert out["motion_std"].shape == (2, 2, 6)
    assert np.all(np.isfinite(out["motion_std"]))
    # bigger column -> different (generally larger) response somewhere
    assert not np.allclose(out["motion_std"][0], out["motion_std"][1])


def test_omdao_headless_compute():
    """assemble_design -> Model -> extract_outputs without OpenMDAO."""
    from raft_tpu.omdao import assemble_design, extract_outputs
    from raft_tpu.core.model import Model

    base = demo_spar(nw_freqs=(0.05, 0.4))
    mem = base["platform"]["members"][0]
    inputs = {
        "mooring_water_depth": [320.0],
        "platform_member1_rA": mem["rA"],
        "platform_member1_rB": mem["rB"],
        "platform_member1_stations": mem["stations"],
        "platform_member1_d": mem["d"],
        "platform_member1_t": mem["t"],
        "platform_member1_l_fill": mem["l_fill"],
        "platform_member1_rho_fill": mem["rho_fill"],
    }
    design = assemble_design(
        inputs, {}, modeling_opts={"settings": base["settings"], "potModMaster": 1,
                                   "cases": base["cases"]},
        turbine_opts={}, mooring_opts={"nlines": 0},
        member_opts={"nmembers": 1, "shapes": ["circ"]}, analysis_opts={},
    )
    design["mooring"] = base["mooring"]  # use the demo mooring directly
    design["turbine"] = base["turbine"]
    model = Model(design)
    model.analyzeUnloaded()
    model.analyzeCases()
    model.calcOutputs()
    model.solveEigen()
    outputs = {}
    extract_outputs(model, outputs)
    assert outputs["Max_Offset"] > 0
    assert outputs["Max_PtfmPitch"] > 0
    assert len(outputs["rigid_body_periods"]) == 6
    assert np.all(outputs["rigid_body_periods"] > 0)


def test_ballast_density_trim():
    from raft_tpu.core.model import Model

    design = demo_spar(nw_freqs=(0.05, 0.4))
    model = Model(design)
    model.analyzeUnloaded(ballast=2)  # density trim
    # unloaded heave should be near zero after trimming
    assert abs(model.results["properties"]["offset_unloaded"][2]) < 0.2


def test_save_responses(tmp_path):
    from raft_tpu.core.model import Model

    design = demo_spar(nw_freqs=(0.05, 0.4))
    model = Model(design)
    model.analyzeCases()
    model.saveResponses(str(tmp_path / "resp"))
    files = list(tmp_path.glob("resp_Case1_WT0.txt"))
    assert len(files) == 1
    lines = open(files[0]).readlines()
    assert len(lines) == model.nw + 1


def test_omdao_ghost_trim_and_ring_stiffeners():
    """Ghost-segment trimming and ring-stiffener->cap conversion
    (reference omdao_raft.py:518-528, 598-635)."""
    from raft_tpu.omdao import assemble_design

    inputs = {
        "mooring_water_depth": [200.0],
        "platform_member1_rA": [0.0, 0.0, -20.0],
        "platform_member1_rB": [0.0, 0.0, 20.0],
        "platform_member1_stations": [0.0, 0.25, 0.5, 0.75, 1.0],
        "platform_member1_d": [10.0, 10.0, 8.0, 6.0, 6.0],
        "platform_member1_t": [0.05],
        "platform_member1_s_ghostA": [0.25],
        "platform_member1_s_ghostB": [0.75],
        "platform_member1_ring_spacing": [0.1],
        "platform_member1_ring_t": [0.02],
        "platform_member1_ring_h": [0.5],
        "platform_member1_cap_stations": [0.0, 0.5, 1.0],
        "platform_member1_cap_t": [0.04, 0.03, 0.04],
    }
    design = assemble_design(
        inputs, {}, modeling_opts={"potModMaster": 1},
        turbine_opts={}, mooring_opts={}, member_opts={"nmembers": 1},
        analysis_opts={})
    mem = design["platform"]["members"][0]
    # endpoints shifted onto the ghost range of the 40 m axis
    assert np.allclose(mem["rA"], [0.0, 0.0, -10.0])
    assert np.allclose(mem["rB"], [0.0, 0.0, 10.0])
    assert mem["stations"][0] == 0.25 and mem["stations"][-1] == 0.75
    # diameters re-gridded onto the trimmed stations
    assert np.allclose(mem["d"], [10.0, 8.0, 6.0])
    # caps: the 0.0/1.0 caps are outside the ghost range and trimmed
    # joints get no caps, so only the 0.5 cap plus ring stiffeners remain
    caps = np.asarray(mem["cap_stations"])
    assert 0.5 in caps
    # rings stay inside the ghost-trimmed range, anchored at s_grid[0]
    assert caps.min() >= 0.25 and caps.max() <= 0.75
    ring_rows = np.asarray(mem["cap_t"]) == 0.02
    # floor(0.5/0.1) = 5 rings at 0.3..0.7; the one colliding with the
    # user cap at 0.5 is dropped in favor of the explicit cap
    assert ring_rows.sum() == 4
    np.testing.assert_allclose(np.sort(caps[ring_rows]), [0.3, 0.4, 0.6, 0.7])
    d_in = np.asarray(mem["cap_d_in"])[ring_rows]
    assert np.all(d_in > 0)  # d - 2*ring_h


def test_omdao_dlc_filter():
    from raft_tpu.omdao import filter_dlc_cases

    keys = ["wind_speed", "turbulence"]
    data = [[8.0, "NTM"], [10.0, "1.1_NTM"], [50.0, "EWM50"], [12.0, "steady"]]
    kept, mask = filter_dlc_cases(keys, data)
    assert len(kept) == 3
    assert mask == [True, True, True, False]


def test_run_raft_farm_driver():
    import raft_tpu

    design = demo_spar(nw_freqs=(0.05, 0.4))
    model = raft_tpu.runRAFTFarm(design)
    assert "case_metrics" in model.results
    assert np.isfinite(model.results["case_metrics"][0][0]["surge_std"])


def test_omdao_save_designs(tmp_path):
    """save_designs checkpoint hook writes pickle+YAML per evaluation."""
    import pickle

    from raft_tpu.omdao import run_raft_omdao

    base = demo_spar(nw_freqs=(0.05, 0.4))
    mem = base["platform"]["members"][0]
    inputs = {
        "mooring_water_depth": [320.0],
        "platform_member1_rA": mem["rA"],
        "platform_member1_rB": mem["rB"],
        "platform_member1_stations": mem["stations"],
        "platform_member1_d": mem["d"],
        "platform_member1_t": mem["t"],
        "platform_member1_l_fill": mem["l_fill"],
        "platform_member1_rho_fill": mem["rho_fill"],
    }
    options = {
        "modeling_options": {"settings": base["settings"], "potModMaster": 1,
                             "cases": base["cases"], "save_designs": True},
        "turbine_options": base["turbine"],
        "mooring_options": {"nlines": 0},
        "member_options": {"nmembers": 1, "shapes": ["circ"]},
        "analysis_options": {"general": {"folder_output": str(tmp_path)}},
    }
    # the demo mooring can't be described by flat arrays here; patch it in
    from raft_tpu import omdao as om_mod
    orig = om_mod.assemble_design

    def patched(*args, **kw):
        d = orig(*args, **kw)
        d["mooring"] = base["mooring"]
        return d

    om_mod.assemble_design = patched
    try:
        model, outputs = run_raft_omdao(inputs, {}, options, i_design=3)
    finally:
        om_mod.assemble_design = orig
    pkl = tmp_path / "raft_designs" / "raft_design_3.pkl"
    yml = tmp_path / "raft_designs" / "raft_design_3.yaml"
    assert pkl.exists() and yml.exists()
    with open(pkl, "rb") as fh:
        d = pickle.load(fh)
    assert d["platform"]["members"][0]["d"] == mem["d"]
    # full WEIS aggregate surface present
    for key in ("Max_Offset", "Max_PtfmPitch", "Std_PtfmPitch", "heave_avg",
                "max_nac_accel", "max_tower_base", "platform_displacement",
                "platform_mass", "platform_I_total", "surge_period"):
        assert key in outputs, key
    assert outputs["stats_surge_std"].shape == (len(base["cases"]["data"]),) or \
        outputs["stats_surge_std"].ndim == 0


def test_omdao_ghost_lfill_regrid():
    """Per-segment l_fill/rho_fill follow the ghost-trimmed station grid."""
    from raft_tpu.omdao import assemble_design

    inputs = {
        "mooring_water_depth": [200.0],
        "platform_member1_rA": [0.0, 0.0, -20.0],
        "platform_member1_rB": [0.0, 0.0, 20.0],
        "platform_member1_stations": [0.0, 0.25, 0.5, 0.75, 1.0],
        "platform_member1_d": [10.0, 10.0, 8.0, 6.0, 6.0],
        "platform_member1_t": [0.05],
        "platform_member1_l_fill": [1.0, 2.0, 3.0, 4.0],
        "platform_member1_rho_fill": [1025.0, 1025.0, 1800.0, 1800.0],
        "platform_member1_s_ghostA": [0.25],
        "platform_member1_s_ghostB": [0.75],
    }
    design = assemble_design(
        inputs, {}, modeling_opts={"potModMaster": 1}, turbine_opts={},
        mooring_opts={}, member_opts={"nmembers": 1}, analysis_opts={})
    mem = design["platform"]["members"][0]
    assert len(mem["stations"]) == 3
    # trimmed segments (0.25-0.5, 0.5-0.75) take the matching source values
    assert mem["l_fill"] == [2.0, 3.0]
    assert mem["rho_fill"] == [1025.0, 1800.0]
    # no-ghost member passes arrays through untouched
    inputs2 = {k: v for k, v in inputs.items()
               if not k.endswith(("s_ghostA", "s_ghostB"))}
    design2 = assemble_design(
        inputs2, {}, modeling_opts={"potModMaster": 1}, turbine_opts={},
        mooring_opts={}, member_opts={"nmembers": 1}, analysis_opts={})
    assert design2["platform"]["members"][0]["l_fill"] == [1.0, 2.0, 3.0, 4.0]


def test_phase_profiling():
    """Structured per-phase timing (SURVEY.md §5 aux subsystem)."""
    from raft_tpu import profiling

    profiling.reset()
    with profiling.phase("outer"):
        with profiling.phase("inner"):
            pass
    rep = profiling.report()
    assert set(rep) == {"outer", "outer/inner"}
    assert rep["outer"] >= rep["outer/inner"] >= 0.0
    assert profiling.counts()["outer"] == 1
    assert "outer/inner" in profiling.summary()
    profiling.reset()

    import raft_tpu

    model = raft_tpu.Model(demo_spar(nw_freqs=(0.05, 0.4)))
    model.analyzeCases()
    rep = profiling.report()
    for key in ("statics", "BEM", "solveStatics", "solveDynamics"):
        assert key in rep, key
    profiling.reset()


def test_sweep_checkpoint_resume(tmp_path):
    """Chunked sweep execution with atomic checkpointing: a re-run of the
    same sweep resumes instead of recomputing (SURVEY.md §5)."""
    from raft_tpu import sweep as sweep_mod

    design = demo_spar(nw_freqs=(0.05, 0.4))
    axes = [("platform.members.0.d",
             [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
              [10.5, 10.5, 6.5, 6.5]])]
    states = [(4.0, 8.0), (6.0, 10.0)]
    ckpt = str(tmp_path / "sweep.npz")

    out1 = sweep_mod.sweep(design, axes, states, n_iter=6,
                           checkpoint=ckpt, chunk_size=2)
    assert np.all(np.isfinite(out1["motion_std"]))

    # resume: no designs left -> no variant parsing/stacking at all
    calls = []
    orig = sweep_mod.stack_variants

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    sweep_mod.stack_variants = spy
    try:
        out2 = sweep_mod.sweep(design, axes, states, n_iter=6,
                               checkpoint=ckpt, chunk_size=2)
    finally:
        sweep_mod.stack_variants = orig
    assert calls == []  # fully resumed from the checkpoint
    np.testing.assert_allclose(out2["motion_std"], out1["motion_std"])

    # a different sweep signature ignores the stale checkpoint and
    # recomputes; the stacked variant batch itself is REUSED from the
    # in-process memo (stacking depends only on design + axis values,
    # not sea states)
    calls.clear()
    sweep_mod.stack_variants = spy
    try:
        out3 = sweep_mod.sweep(design, axes, [(5.0, 9.0)], n_iter=6,
                               checkpoint=ckpt, chunk_size=2)
    finally:
        sweep_mod.stack_variants = orig
    assert calls == []  # same axes -> stacked batch served from the memo
    assert out3["motion_std"].shape == (3, 1, 6)
    assert np.all(np.isfinite(out3["motion_std"]))
    assert not np.allclose(out3["motion_std"][:, 0], out1["motion_std"][:, 0])

    # changing an axis VALUE defeats the stack memo: the batch rebuilds
    axes2 = [("platform.members.0.d",
              [[9.5, 9.5, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
               [10.5, 10.5, 6.5, 6.5]])]
    calls.clear()
    sweep_mod.stack_variants = spy
    try:
        out4 = sweep_mod.sweep(design, axes2, [(5.0, 9.0)], n_iter=6,
                               chunk_size=2)
    finally:
        sweep_mod.stack_variants = orig
    assert len(calls) == 1
    assert not np.allclose(out4["motion_std"], out3["motion_std"])


def test_reference_api_surface(tmp_path):
    """Reference-named convenience APIs exist and run: plotting
    (Model/FOWT/Rotor), addFOWT, floris* wrappers, IECKaimal alias."""
    import matplotlib

    matplotlib.use("Agg")
    import raft_tpu

    model = raft_tpu.Model(demo_spar(nw_freqs=(0.05, 0.4)))
    model.analyzeCases()
    assert model.plot() is not None
    assert model.plot2d() is not None
    model.plotResponses_extended()
    fowt = model.fowtList[0]
    assert fowt.plot() is not None and fowt.plot2d() is not None
    rotor = fowt.rotorList[0]
    assert rotor.plot() is not None
    case = dict(zip(model.design["cases"]["keys"], model.design["cases"]["data"][0]))
    case["wind_speed"], case["turbulence"] = 10.0, 0.14
    U, V, W, Rot = rotor.IECKaimal(case)
    assert np.max(np.asarray(U)) > 0  # Kaimal PSD is live
    n0 = model.nFOWT
    model.addFOWT(fowt, (1600, 0))
    assert model.nFOWT == n0 + 1

    # floris-style wrappers exist and delegate to the farm wake layer
    for name in ("powerThrustCurve", "florisCoupling",
                 "florisFindEquilibrium", "florisCalcAEP"):
        assert callable(getattr(model, name)), name


def test_omdao_turbine_assembly():
    """Flat OM turbine inputs rebuild a working turbine dict
    (omdao_raft.py:424-499): IEA15MW flattened -> assembled -> Rotor
    runs calcAero with results matching the dict-driven rotor."""
    import yaml

    from raft_tpu.omdao import assemble_design
    from raft_tpu.rotor.rotor import Rotor

    with open("/root/reference/tests/test_data/IEA15MW.yaml") as f:
        ref = yaml.load(f, Loader=yaml.FullLoader)
    t = ref["turbine"]
    geom = np.asarray(t["blade"]["geometry"], dtype=float)
    afs = t["airfoils"]
    # common AoA grid like WEIS provides (per-airfoil polars differ in length)
    aoa_deg = np.linspace(-180.0, 180.0, 100)
    aoa = np.radians(aoa_deg)

    def resample(a, col):
        tab = np.asarray(a["data"], dtype=float)
        return np.interp(aoa_deg, tab[:, 0], tab[:, col])
    inputs = {
        "mooring_water_depth": [200.0],
        "turbine_mRNA": [t["mRNA"]], "turbine_IxRNA": [t["IxRNA"]],
        "turbine_IrRNA": [t["IrRNA"]], "turbine_xCG_RNA": [t["xCG_RNA"]],
        "turbine_hHub": [t["hHub"]], "turbine_overhang": [t["overhang"]],
        "turbine_tower_rA": t["tower"]["rA"], "turbine_tower_rB": t["tower"]["rB"],
        "turbine_tower_gamma": [0.0],
        "turbine_tower_stations": t["tower"]["stations"],
        "turbine_tower_d": t["tower"]["d"], "turbine_tower_t": t["tower"]["t"],
        "turbine_tower_Cd": t["tower"]["Cd"], "turbine_tower_Ca": t["tower"]["Ca"],
        "turbine_tower_CdEnd": t["tower"]["CdEnd"],
        "turbine_tower_CaEnd": t["tower"]["CaEnd"],
        "turbine_tower_rho_shell": [t["tower"]["rho_shell"]],
        "tilt": [t["shaft_tilt"]], "precone": [t["precone"]],
        "wind_reference_height": [t["Zhub"]], "hub_radius": [t["Rhub"]],
        "rotor_inertia": [t.get("I_drivetrain", 0.0)],
        "blade_r": geom[:, 0], "blade_chord": geom[:, 1],
        "blade_theta": geom[:, 2], "blade_precurve": geom[:, 3],
        "blade_presweep": geom[:, 4],
        "blade_Rtip": [t["blade"]["Rtip"]],
        "blade_precurveTip": [t["blade"].get("precurveTip", 0.0)],
        "blade_presweepTip": [t["blade"].get("presweepTip", 0.0)],
        "airfoils_position": [p for p, _ in t["blade"]["airfoils"]],
        "airfoils_aoa": aoa,
        "airfoils_cl": np.stack([resample(a, 1) for a in afs])[:, :, None, None],
        "airfoils_cd": np.stack([resample(a, 2) for a in afs])[:, :, None, None],
        "airfoils_cm": np.stack([resample(a, 3) for a in afs])[:, :, None, None],
        "airfoils_r_thick": [a["relative_thickness"] for a in afs],
        "rotor_powercurve_v": t["wt_ops"]["v"],
        "rotor_powercurve_omega_rpm": t["wt_ops"]["omega_op"],
        "rotor_powercurve_pitch": t["wt_ops"]["pitch_op"],
    }
    dins = {"nBlades": t["nBlades"],
            "airfoils_name": [a["name"] for a in afs]}
    design = assemble_design(
        inputs, dins, modeling_opts={"potModMaster": 1},
        turbine_opts={"af_used_names": [n for _, n in t["blade"]["airfoils"]],
                      "shape": "circ"},
        mooring_opts={}, member_opts={"nmembers": 0}, analysis_opts={})
    ta = design["turbine"]
    assert ta["nBlades"] == t["nBlades"]
    np.testing.assert_allclose(np.asarray(ta["blade"]["geometry"]), geom)

    # the assembled turbine drives the BEM rotor like the dict-driven one
    w = np.arange(0.05, 0.4, 0.05) * 2 * np.pi
    for tt in (ta,):
        tt["nrotors"] = 1
        if isinstance(tt.get("tower"), dict):
            tt["tower"] = [tt["tower"]]
        for k, d in [("rho_air", 1.225), ("mu_air", 1.81e-05), ("shearExp_air", 0.12),
                     ("rho_water", 1025.0), ("mu_water", 1.0e-03), ("shearExp_water", 0.12)]:
            tt[k] = d
    rotor = Rotor(ta, w, 0)
    rotor.setPosition()
    case = {"wind_speed": 10.0, "wind_heading": 0, "turbulence": 0,
            "turbine_status": "operating", "yaw_misalign": 0}
    f0, f, a, b = rotor.calcAero(case)
    assert np.isfinite(np.asarray(f0)).all()
    assert abs(np.asarray(f0)[0]) > 1e5  # thrust-scale force present


def test_legacy_runraft_driver(tmp_path):
    """The deprecated standalone driver module (reference runRAFT.py:21-64):
    YAML file in, analyzed model out, legacy defaults applied."""
    import warnings

    import yaml as _yaml

    from raft_tpu import runRAFT as legacy

    design = demo_spar(nw_freqs=(0.05, 0.4))
    design.setdefault("name", "demo spar")
    path = tmp_path / "design.yaml"
    from raft_tpu.io_utils import clean_raft_dict

    path.write_text(_yaml.safe_dump(clean_raft_dict(design)))

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        model = legacy.runRAFT(str(path))
    assert any(issubclass(r.category, DeprecationWarning) for r in rec)
    # legacy flow ran end to end: eigen + one default case analyzed
    assert "eigen" in model.results
    m = model.results["case_metrics"][0][0]
    assert np.isfinite(m["surge_std"]) and m["surge_std"] > 0
    # legacy grid: w = 0.05..5 rad/s
    assert np.isclose(model.w[0], 0.05, rtol=1e-6)

    with pytest.raises(NotImplementedError):
        legacy.runRAFTfromWEIS()
