"""Aux subsystem tests: IEC transient winds, sweep driver, OMDAO-style
headless compute, ballast trim, response export."""

import numpy as np
import pytest

from raft_tpu.rotor.wind import IECWindExtreme
from raft_tpu.designs import demo_spar


def test_iec_sigma_models():
    iec = IECWindExtreme()
    iec.Turbine_Class = "I"
    iec.Turbulence_Class = "B"
    iec.setup()
    assert iec.V_ref == 50.0 and iec.I_ref == 0.14
    # NTM at 10 m/s: 0.14*(7.5+5.6)
    assert np.isclose(iec.NTM(10.0), 0.14 * (0.75 * 10 + 5.6))
    sig, V_e50, V_e1, _, _ = iec.EWM(10.0)
    assert np.isclose(V_e50, 70.0) and np.isclose(V_e1, 56.0)


@pytest.mark.parametrize("event", ["EOG", "EDC", "ECD", "EWS"])
def test_iec_transients(event, tmp_path):
    iec = IECWindExtreme()
    iec.setup()
    t, cols = getattr(iec, event)(12.0)
    assert t[0] == 0.0 and len(t) > 100
    for k in ("V", "V_dir", "V_gust", "shear_vert"):
        assert len(cols[k]) == len(t)
        assert np.all(np.isfinite(cols[k]))
    if event == "EOG":
        assert cols["V_gust"].min() < -0.1  # gust dips
    if event == "EDC":
        assert abs(cols["V_dir"][-1]) > 5  # ends at full direction change
    path = iec.write_wnd(str(tmp_path / "x.wnd"), t, cols)
    assert len(open(path).readlines()) == len(t) + 3


def test_sweep_driver():
    from raft_tpu.sweep import sweep

    design = demo_spar(nw_freqs=(0.05, 0.4))
    out = sweep(
        design,
        axes=[("platform.members.0.d", [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5]])],
        sea_states=[(4.0, 8.0), (6.0, 10.0)],
        n_iter=8,
    )
    assert len(out["grid"]) == 2
    assert out["motion_std"].shape == (2, 2, 6)
    assert np.all(np.isfinite(out["motion_std"]))
    # bigger column -> different (generally larger) response somewhere
    assert not np.allclose(out["motion_std"][0], out["motion_std"][1])


def test_omdao_headless_compute():
    """assemble_design -> Model -> extract_outputs without OpenMDAO."""
    from raft_tpu.omdao import assemble_design, extract_outputs
    from raft_tpu.core.model import Model

    base = demo_spar(nw_freqs=(0.05, 0.4))
    mem = base["platform"]["members"][0]
    inputs = {
        "mooring_water_depth": [320.0],
        "platform_member1_rA": mem["rA"],
        "platform_member1_rB": mem["rB"],
        "platform_member1_stations": mem["stations"],
        "platform_member1_d": mem["d"],
        "platform_member1_t": mem["t"],
        "platform_member1_l_fill": mem["l_fill"],
        "platform_member1_rho_fill": mem["rho_fill"],
    }
    design = assemble_design(
        inputs, {}, modeling_opts={"settings": base["settings"], "potModMaster": 1,
                                   "cases": base["cases"]},
        turbine_opts={}, mooring_opts={"nlines": 0},
        member_opts={"nmembers": 1, "shapes": ["circ"]}, analysis_opts={},
    )
    design["mooring"] = base["mooring"]  # use the demo mooring directly
    design["turbine"] = base["turbine"]
    model = Model(design)
    model.analyzeUnloaded()
    model.analyzeCases()
    model.calcOutputs()
    model.solveEigen()
    outputs = {}
    extract_outputs(model, outputs)
    assert outputs["Max_Offset"] > 0
    assert outputs["Max_PtfmPitch"] > 0
    assert len(outputs["rigid_body_periods"]) == 6
    assert np.all(outputs["rigid_body_periods"] > 0)


def test_ballast_density_trim():
    from raft_tpu.core.model import Model

    design = demo_spar(nw_freqs=(0.05, 0.4))
    model = Model(design)
    model.analyzeUnloaded(ballast=2)  # density trim
    # unloaded heave should be near zero after trimming
    assert abs(model.results["properties"]["offset_unloaded"][2]) < 0.2


def test_save_responses(tmp_path):
    from raft_tpu.core.model import Model

    design = demo_spar(nw_freqs=(0.05, 0.4))
    model = Model(design)
    model.analyzeCases()
    model.saveResponses(str(tmp_path / "resp"))
    files = list(tmp_path.glob("resp_Case1_WT0.txt"))
    assert len(files) == 1
    lines = open(files[0]).readlines()
    assert len(lines) == model.nw + 1
