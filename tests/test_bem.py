"""Panel mesher + native BEM solver tests.

The BEM accuracy benchmark is the floating hemisphere (Hulme 1982).
Current agreement is order-correct but not converged (see project task
list): heave added mass within ~30%, radiation damping positive with
the right frequency trend.  Tests pin the structural invariants and
the current accuracy band so regressions are caught while the solver
is refined.
"""

import numpy as np
import pytest

from raft_tpu.hydro.mesh import PanelMesh
from raft_tpu.hydro.potential_bem import PanelBEM


@pytest.fixture(scope="module")
def hemisphere():
    R = 1.0
    zs = np.linspace(-R, 0, 12)
    ds = 2.0 * np.sqrt(np.maximum(R**2 - zs**2, 0.0))
    mesh = PanelMesh()
    mesh.add_member(zs - zs[0], ds, rA=np.array([0.0, 0.0, zs[0]]),
                    rB=np.array([0.0, 0.0, 0.0]), dz_max=0.15, da_max=0.35)
    return mesh


def test_mesh_geometry(hemisphere):
    A, C, N = hemisphere.areas_centroids_normals()
    wet = C[:, 2] < -1e-6  # exclude the waterplane lid the mesher emits
    # wetted area of a unit hemisphere = 2*pi
    assert abs(A[wet].sum() - 2 * np.pi) / (2 * np.pi) < 0.15
    # closed-surface divergence check: |sum(z nz A)| ~ V = 2/3 pi
    vol = abs(np.sum(C[wet, 2] * N[wet, 2] * A[wet]))
    assert abs(vol - 2 * np.pi / 3) / (2 * np.pi / 3) < 0.1
    assert np.all(C[:, 2] <= 1e-9)


def test_pnl_writer(tmp_path, hemisphere):
    path = hemisphere.write_pnl(str(tmp_path))
    text = open(path).read()
    assert "Hull Mesh File" in text
    assert f"{len(hemisphere.panels)}" in text
    gdf = hemisphere.write_gdf(str(tmp_path / "m.gdf"))
    assert len(open(gdf).readlines()) == 4 + 4 * len(hemisphere.panels)


def test_bem_hemisphere_radiation(hemisphere):
    bem = PanelBEM(hemisphere, rho=1000.0, g=9.81)
    ka = np.array([0.2, 1.0])
    w = np.sqrt(9.81 * ka)
    A, B, X = bem.solve(w, ka, headings_deg=[0.0])
    V = 2 / 3 * np.pi

    # symmetry: surge-sway identical, cross-coupling small
    assert np.allclose(A[0, 0], A[1, 1], rtol=0.05)
    assert abs(A[0, 1, 0]) < 0.05 * abs(A[0, 0, 0])
    # damping must be non-negative (radiated energy)
    assert B[2, 2, :].min() > 0
    assert B[0, 0, :].min() > -1e-3 * abs(B[0, 0, :]).max()

    # current accuracy band vs Hulme (1982): order-correct
    mu33 = A[2, 2, :] / (1000.0 * V)
    assert 0.3 < mu33[1] < 0.9  # Hulme: 0.5861 at ka=1
    assert 0.5 < mu33[0] < 1.1  # Hulme: ~0.79 at ka=0.2

    # heave excitation magnitude ~ rho g Awp at long waves
    X3 = abs(X[0, 2, 0])
    assert 0.5 < X3 / (1000.0 * 9.81 * np.pi) < 1.2


def test_bem_in_calcbem_path(tmp_path):
    """FOWT.calcBEM runs the mesher + solver for potMod members."""
    import jax.numpy as jnp  # noqa: F401  (env init)
    from raft_tpu.core.fowt import FOWT
    from raft_tpu.designs import demo_spar

    design = demo_spar(nw_freqs=(0.05, 0.3))
    design["platform"]["potModMaster"] = 0  # 1 would force potMod off
    design["platform"]["members"][0]["potMod"] = True
    w = np.arange(0.05, 0.3, 0.05) * 2 * np.pi
    fowt = FOWT(design, w, depth=320.0)
    fowt.setPosition(np.zeros(6))
    fowt.calcStatics()
    fowt.calcBEM(dz=4.0, da=4.0, meshDir=str(tmp_path))
    assert np.any(fowt.A_BEM != 0)
    assert np.all(np.isfinite(fowt.A_BEM))
    assert np.any(np.abs(fowt.X_BEM) > 0)
    assert (tmp_path / "HullMesh.pnl").exists()
