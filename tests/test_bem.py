"""Panel mesher + native BEM solver tests.

Accuracy validation strategy (no external oracle is available in this
environment — pyHAMS/WAMIT/Capytaine are not installed):

1.  The free-surface Green function is validated pointwise elsewhere
    (analytic A=0 closed form, free-surface boundary condition) — see
    ``raft_tpu/hydro/greens.py``.
2.  The solution of the integral equation is validated through the
    Haskind energy identity: pressure-integrated radiation damping must
    equal the damping implied by the excitation force (deep water,
    B33 = k*w*|X3|^2 / (2*rho*g^2) for an axisymmetric body; surge
    carries an extra cos^2 heading average of 1/2).  This identity holds
    only for solutions of the true boundary-value problem, so it catches
    formulation errors that mesh-convergence studies cannot.
3.  Known exact limits of the floating hemisphere: heave added-mass
    coefficient -> 0.8310 as ka -> 0 (Hulme 1982; approached from above
    through a +ka*ln(ka) hump), -> 0.5 as ka -> inf (doubled-body
    sphere), with the characteristic dip below 0.5 near ka ~ 2.
4.  The fast one-point/table solver (`PanelBEM`) is pinned against the
    rigorous subpanel-quadrature solver (`RefPanelBEM`) to 5-6%
    (the measured gap on this mesh is ~2-3%).

Historical note: an earlier revision pinned mid-range "Hulme" values
(mu33 = 0.5861 at ka = 1) that were written from memory and are not
consistent with the energy identity or the published shape of the
hemisphere curves; the solver disagreed with them by ~22% while being
energy-consistent to ~2%.  Those numbers were the bug.
"""

import numpy as np
import pytest

from raft_tpu.hydro.mesh import PanelMesh
from raft_tpu.hydro.potential_bem import PanelBEM
from raft_tpu.hydro.bem_ref import RefPanelBEM

RHO = 1000.0
G = 9.81
HEMI_V = 2 / 3 * np.pi


def hemi_mesh(npts=25, dz=0.15, da=0.35):
    R = 1.0
    zs = np.linspace(-R, 0, npts)
    ds = 2.0 * np.sqrt(np.maximum(R**2 - zs**2, 0.0))
    mesh = PanelMesh()
    mesh.add_member(zs - zs[0], ds, rA=np.array([0.0, 0.0, zs[0]]),
                    rB=np.array([0.0, 0.0, 0.0]), dz_max=dz, da_max=da)
    return mesh


@pytest.fixture(scope="module")
def hemisphere():
    return hemi_mesh()


@pytest.fixture(scope="module")
def hemi_solution(hemisphere):
    bem = PanelBEM(hemisphere, rho=RHO, g=G)
    ka = np.array([0.05, 0.2, 1.0, 2.0, 4.0])
    w = np.sqrt(G * ka)
    A, B, X = bem.solve(w, ka, headings_deg=[0.0])
    return ka, w, A, B, X


def test_mesh_geometry(hemisphere):
    A, C, N = hemisphere.areas_centroids_normals()
    wet = C[:, 2] < -1e-6  # exclude the waterplane lid the mesher emits
    # wetted area of a unit hemisphere = 2*pi
    assert abs(A[wet].sum() - 2 * np.pi) / (2 * np.pi) < 0.15
    # closed-surface divergence check: |sum(z nz A)| ~ V = 2/3 pi
    vol = abs(np.sum(C[wet, 2] * N[wet, 2] * A[wet]))
    assert abs(vol - 2 * np.pi / 3) / (2 * np.pi / 3) < 0.1
    assert np.all(C[:, 2] <= 1e-9)


def test_pnl_writer(tmp_path, hemisphere):
    path = hemisphere.write_pnl(str(tmp_path))
    text = open(path).read()
    assert "Hull Mesh File" in text
    assert f"{len(hemisphere.panels)}" in text
    gdf = hemisphere.write_gdf(str(tmp_path / "m.gdf"))
    assert len(open(gdf).readlines()) == 4 + 4 * len(hemisphere.panels)


def test_hemisphere_structure(hemi_solution):
    ka, w, A, B, X = hemi_solution
    # symmetry: surge-sway identical, cross-coupling small
    assert np.allclose(A[0, 0], A[1, 1], rtol=0.05)
    assert abs(A[0, 1, 0]) < 0.05 * abs(A[0, 0, 0])
    # damping must be non-negative (radiated energy)
    assert B[2, 2, :].min() > 0
    assert B[0, 0, :].min() > -1e-3 * abs(B[0, 0, :]).max()
    # long waves: heave excitation -> rho*g*Awp (Froude-Krylov limit)
    assert abs(X[0, 2, 0]) / (RHO * G * np.pi) == pytest.approx(1.0, abs=0.12)


def test_hemisphere_energy_identity(hemi_solution):
    """Pressure-integrated damping == Haskind/far-field energy damping."""
    ka, w, A, B, X = hemi_solution
    for i in range(len(ka)):
        B33_energy = ka[i] * w[i] * abs(X[0, 2, i]) ** 2 / (2 * RHO * G**2)
        assert B[2, 2, i] == pytest.approx(B33_energy, rel=0.08)
        if 0.2 <= ka[i] <= 2.0:
            # below 0.2 surge damping is too small to compare; above ~2.5
            # the source formulation nears the hemisphere's first interior
            # (irregular) frequency and both solvers lose a few 10s of %
            B11_energy = ka[i] * w[i] * abs(X[0, 0, i]) ** 2 / (4 * RHO * G**2)
            assert B[0, 0, i] == pytest.approx(B11_energy, rel=0.10)


def test_hemisphere_limits(hemi_solution):
    """Known exact limits of the floating hemisphere (Hulme 1982)."""
    ka, w, A, B, X = hemi_solution
    mu33 = A[2, 2, :] / (RHO * HEMI_V)
    # ka->0 limit is 0.8310, approached from above (ka*ln ka hump)
    assert 0.83 < mu33[0] < 0.97          # ka = 0.05
    assert 0.78 < mu33[1] < 0.88          # ka = 0.2
    # characteristic dip below the 0.5 high-frequency limit near ka ~ 2
    assert mu33[3] < 0.5                  # ka = 2.0
    assert mu33[3] < mu33[4] < 0.55       # recovering toward 0.5 at ka = 4
    # surge: ka->0 limit is 0.5 (doubled-body full sphere)
    mu11 = A[0, 0, :] / (RHO * HEMI_V)
    assert 0.49 < mu11[0] < 0.60


def test_fast_vs_rigorous_quadrature(hemisphere):
    """One-point/table PanelBEM tracks the subpanel-quadrature RefPanelBEM."""
    ka = np.array([0.2, 1.0])
    w = np.sqrt(G * ka)
    fast = PanelBEM(hemisphere, rho=RHO, g=G)
    slow = RefPanelBEM(hemisphere, rho=RHO, g=G)
    Af, Bf, Xf = fast.solve(w, ka, headings_deg=[0.0])
    As, Bs, Xs = slow.solve(w, ka, headings_deg=[0.0])
    for i in range(len(ka)):
        assert Af[2, 2, i] == pytest.approx(As[2, 2, i], rel=0.05)
        assert Af[0, 0, i] == pytest.approx(As[0, 0, i], rel=0.05)
        assert Bf[2, 2, i] == pytest.approx(Bs[2, 2, i], rel=0.06)
        assert abs(Xf[0, 2, i]) == pytest.approx(abs(Xs[0, 2, i]), rel=0.06)


def test_bem_in_calcbem_path(tmp_path):
    """FOWT.calcBEM runs the mesher + solver for potMod members."""
    import jax.numpy as jnp  # noqa: F401  (env init)
    from raft_tpu.core.fowt import FOWT
    from raft_tpu.designs import demo_spar

    design = demo_spar(nw_freqs=(0.05, 0.3))
    design["platform"]["potModMaster"] = 0  # 1 would force potMod off
    design["platform"]["members"][0]["potMod"] = True
    w = np.arange(0.05, 0.3, 0.05) * 2 * np.pi
    fowt = FOWT(design, w, depth=320.0)
    fowt.setPosition(np.zeros(6))
    fowt.calcStatics()
    fowt.calcBEM(dz=4.0, da=4.0, meshDir=str(tmp_path))
    assert np.any(fowt.A_BEM != 0)
    assert np.all(np.isfinite(fowt.A_BEM))
    assert np.any(np.abs(fowt.X_BEM) > 0)
    assert (tmp_path / "HullMesh.pnl").exists()


def test_finite_depth_energy_and_deep_limit():
    """Finite-depth John-kernel solver: Haskind energy identity in the
    strongly finite-depth regime, and agreement with the deep-water
    solver when kh is large."""
    from raft_tpu.hydro.greens_fd import wavenumber

    mesh = hemi_mesh()
    h = 2.0  # depth = 2 radii
    Ks = np.array([0.2, 1.0])
    ks = np.array([wavenumber(K, h) for K in Ks])
    ws = np.sqrt(G * Ks)
    bem = PanelBEM(mesh, rho=RHO, g=G, depth=h)
    A, B, X = bem.solve(ws, ks, headings_deg=[0.0])
    for i in range(len(Ks)):
        k, w = ks[i], ws[i]
        Cg = (w / (2 * k)) * (1 + 2 * k * h / np.sinh(2 * k * h))
        B33_energy = k * abs(X[0, 2, i]) ** 2 / (4 * RHO * G * Cg)
        assert B[2, 2, i] == pytest.approx(B33_energy, rel=0.06)
        assert A[2, 2, i] > 0

    # near the kernel switch (kh just under 6, the deepest the John
    # branch runs): finite-depth solver reproduces the deep-water solver
    ka = np.array([1.0])
    wd = np.sqrt(G * ka)
    Ad, Bd, Xd = PanelBEM(mesh, rho=RHO, g=G).solve(wd, ka, headings_deg=[0.0])
    h2 = 5.5
    k2 = np.array([wavenumber(K, h2) for K in ka])
    bem2 = PanelBEM(mesh, rho=RHO, g=G, depth=h2)
    A2, B2, X2 = bem2.solve(wd, k2, headings_deg=[0.0])
    assert len(bem2._fd_tables) == 1  # the John branch actually ran
    assert A2[2, 2, 0] == pytest.approx(Ad[2, 2, 0], rel=0.02)
    assert B2[2, 2, 0] == pytest.approx(Bd[2, 2, 0], rel=0.02)
    assert abs(X2[0, 2, 0]) == pytest.approx(abs(Xd[0, 2, 0]), rel=0.02)
    # and past the switch the deep branch serves without table builds
    h3 = 12.0
    k3 = np.array([wavenumber(K, h3) for K in ka])
    bem3 = PanelBEM(mesh, rho=RHO, g=G, depth=h3)
    A3, _, _ = bem3.solve(wd, k3, headings_deg=[0.0])
    assert len(bem3._fd_tables) == 0
    assert A3[2, 2, 0] == pytest.approx(Ad[2, 2, 0], rel=0.01)


def test_irr_removal_suppresses_interior_resonance():
    """The experimental interior-lid option (extended boundary condition)
    damps the surge energy-identity violation at the hemisphere's
    interior resonance near ka = 4, while staying sane elsewhere."""
    mesh = hemi_mesh()
    ka = np.array([4.0])
    w = np.sqrt(G * ka)

    def surge_identity_err(bem):
        A, B, X = bem.solve(w, ka, headings_deg=[0.0])
        B11_energy = ka[0] * w[0] * abs(X[0, 0, 0]) ** 2 / (4 * RHO * G**2)
        return abs(B[0, 0, 0] / B11_energy - 1.0), A

    err_plain, _ = surge_identity_err(PanelBEM(mesh, rho=RHO, g=G))
    bem_irr = PanelBEM(mesh, rho=RHO, g=G, irr_removal=True)
    assert bem_irr.nl > 0  # the mesher's z=0 cap became the lid
    err_irr, A_irr = surge_identity_err(bem_irr)
    assert err_plain > 0.15          # the resonance is visible without the lid
    assert err_irr < 0.6 * err_plain  # and substantially suppressed with it
    assert 0.3 < A_irr[2, 2, 0] / (RHO * HEMI_V) < 0.6  # physics still sane


def test_fd_quadrature_paths_agree():
    """The three finite-depth PV quadrature paths — vectorized jnp
    (accelerator default), native C++, NumPy — agree on random points.
    The jnp path uses per-point fixed-count tails, the scalar paths
    adaptive counts, so agreement is to quadrature tolerance.  Lives
    here (not test_native) so it runs even without a C++ toolchain —
    the "native" mode then falls back to NumPy internally."""
    import os

    import numpy as np

    from raft_tpu.hydro import greens_fd

    K, h = 0.05, 200.0
    k = greens_fd.wavenumber(K, h)
    rng = np.random.default_rng(0)
    R = rng.uniform(0.0, 80.0, 300)
    u = -rng.uniform(0.0, 2 * h, 300)
    w = rng.uniform(0.0, h, 300)

    def run(mode):
        prev = os.environ.get("RAFT_TPU_FD_QUAD")
        os.environ["RAFT_TPU_FD_QUAD"] = mode
        try:
            return (greens_fd._pv_fd(R, u, K, h, k, 1),
                    greens_fd._pv_fd(R, w, K, h, k, 2))
        finally:
            if prev is None:
                del os.environ["RAFT_TPU_FD_QUAD"]
            else:
                os.environ["RAFT_TPU_FD_QUAD"] = prev

    j1, j2 = run("jnp")
    n1, n2 = run("native")
    p1, p2 = run("numpy")
    s1 = np.max(np.abs(p1))
    s2 = np.max(np.abs(p2))
    assert np.max(np.abs(j1 - p1)) < 1e-3 * s1
    assert np.max(np.abs(n1 - p1)) < 1e-3 * s1
    assert np.max(np.abs(j2 - p2)) < 1e-6 * s2
    assert np.max(np.abs(n2 - p2)) < 1e-6 * s2

    # the K-blocked batch builder produces well-formed tables (full
    # batch-vs-single equality is checked on the accelerator path)
    tabs = greens_fd.build_tables_batch([0.04, 0.07], h, 80.0, n_R=32, n_s=24)
    for K_, tab in tabs.items():
        arrs = tab.jarrays()
        assert all(np.all(np.isfinite(np.asarray(a))) for a in arrs)
