"""Batched on-device BEM tier tests (raft_tpu.hydro.bem_batch).

Validation strategy:

1.  Assembly parity — the Pallas Rankine kernel (interpret mode on CPU)
    against the pure-jnp reference assembly, elementwise.
2.  Solver parity — a single design through ``solve_panel_batch`` must
    reproduce ``PanelBEM.solve`` (the per-design solver validated in
    tests/test_bem.py against energy identities, RefPanelBEM and the
    native C++ kernels) to machine precision, deep water AND finite
    depth.
3.  Padding exactness — bucketed N_max padding must contribute EXACT
    zeros (padded columns) and identity rows, so real-panel results are
    bit-identical at a fixed program shape; across DIFFERENT bucket
    shapes results agree to reduction-order tolerance only, which is
    also pinned here.
4.  Sweep integration — potMod configurations run the batched path end
    to end (no SweepAxisError fallback, no dropped-coefficient
    warnings), BEM-off routes to the per-variant fallback with the
    capability warning, and BEM-off sweeps compile zero extra XLA
    programs (the seed-trace contract).
"""

import os
import warnings

import numpy as np
import pytest

from raft_tpu.hydro import bem_batch
from raft_tpu.hydro.bem_batch import (rankine_matrices_batch,
                                      solve_panel_batch)
from raft_tpu.hydro.mesh import PanelMesh
from raft_tpu.hydro.potential_bem import PanelBEM

RHO = 1000.0
G = 9.81


def hemi_mesh(npts=18, dz=0.22, da=0.5, R=1.0):
    zs = np.linspace(-R, 0, npts)
    ds = 2.0 * np.sqrt(np.maximum(R**2 - zs**2, 0.0))
    mesh = PanelMesh()
    mesh.add_member(zs - zs[0], ds, rA=np.array([0.0, 0.0, zs[0]]),
                    rB=np.array([0.0, 0.0, 0.0]), dz_max=dz, da_max=da)
    return mesh


def panels_of(bem):
    """(areas, centroids, normals) as solve_panel_batch consumes them —
    PanelBEM has already applied the identical mask/orientation rules."""
    return (np.asarray(bem.areas), np.asarray(bem.centroids),
            np.asarray(bem.normals))


@pytest.fixture(scope="module")
def hemi_bem():
    return PanelBEM(hemi_mesh(), rho=RHO, g=G)


# ---------------------------------------------------------------------------
# assembly parity: pallas (interpret on CPU) vs jnp
# ---------------------------------------------------------------------------


def test_rankine_pallas_vs_jnp(hemi_bem):
    import jax.numpy as jnp

    pan = panels_of(hemi_bem)
    Nmax = bem_batch._bucket_size(len(pan[0]))
    A, C, Nrm, msk, modes = bem_batch._stack_bucket([pan, pan], Nmax)
    S_j, D_j = rankine_matrices_batch(C, A, Nrm, mode="jnp")
    S_p, D_p = rankine_matrices_batch(C, A, Nrm, mode="pallas")
    np.testing.assert_allclose(np.asarray(S_p), np.asarray(S_j),
                               rtol=1e-12, atol=1e-13)
    np.testing.assert_allclose(np.asarray(D_p), np.asarray(D_j),
                               rtol=1e-12, atol=1e-13)
    # both designs in the stack are the same panels: rows must agree
    np.testing.assert_array_equal(np.asarray(S_j[0]), np.asarray(S_j[1]))


def test_assembly_choice_modes(monkeypatch):
    impl, interp = bem_batch.assembly_choice("jnp")
    assert (impl, interp) == ("jnp", False)
    impl, interp = bem_batch.assembly_choice("pallas")
    assert impl == "pallas"
    import jax
    assert interp == (jax.default_backend() != "tpu")
    impl, _ = bem_batch.assembly_choice("auto")
    assert impl == ("pallas" if jax.default_backend() == "tpu" else "jnp")
    with pytest.raises(ValueError):
        bem_batch.assembly_choice("nope")


# ---------------------------------------------------------------------------
# solver parity: batched tier vs PanelBEM.solve
# ---------------------------------------------------------------------------


def test_single_design_matches_panelbem_deep(hemi_bem):
    ka = np.array([0.2, 1.0, 2.5])
    w = np.sqrt(G * ka)
    A_ref, B_ref, X_ref = hemi_bem.solve(w, ka, headings_deg=[0.0, 45.0])
    out = solve_panel_batch([panels_of(hemi_bem)], w, ka,
                            headings_deg=[0.0, 45.0], rho=RHO, g=G)
    # PanelBEM layout: A [6,6,nw], X [nh,6,nw]; tier: [nd,nw,6,6]/[nd,nbh,6,nw]
    np.testing.assert_allclose(out["Abem"][0], np.moveaxis(A_ref, 2, 0),
                               rtol=1e-10, atol=1e-10 * abs(A_ref).max())
    np.testing.assert_allclose(out["Bbem"][0], np.moveaxis(B_ref, 2, 0),
                               rtol=1e-10, atol=1e-10 * abs(B_ref).max())
    Xb = out["Xbre"][0] + 1j * out["Xbim"][0]
    np.testing.assert_allclose(Xb, X_ref,
                               rtol=1e-10, atol=1e-10 * abs(X_ref).max())
    np.testing.assert_allclose(out["bem_h"][0], np.radians([0.0, 45.0]))


def test_single_design_matches_panelbem_finite_depth():
    from raft_tpu.hydro.greens_fd import wavenumber

    h = 2.0
    bem = PanelBEM(hemi_mesh(), rho=RHO, g=G, depth=h)
    Ks = np.array([0.2, 1.0])
    ks = np.array([wavenumber(K, h) for K in Ks])
    ws = np.sqrt(G * Ks)
    assert np.all(ks * h < 6.0)  # the John branch actually runs
    A_ref, B_ref, X_ref = bem.solve(ws, ks, headings_deg=[0.0])
    out = solve_panel_batch([panels_of(bem)], ws, ks, headings_deg=[0.0],
                            depth=h, rho=RHO, g=G)
    np.testing.assert_allclose(out["Abem"][0], np.moveaxis(A_ref, 2, 0),
                               rtol=1e-9, atol=1e-9 * abs(A_ref).max())
    np.testing.assert_allclose(out["Bbem"][0], np.moveaxis(B_ref, 2, 0),
                               rtol=1e-9, atol=1e-9 * abs(B_ref).max())
    Xb = out["Xbre"][0] + 1j * out["Xbim"][0]
    np.testing.assert_allclose(Xb, X_ref,
                               rtol=1e-9, atol=1e-9 * abs(X_ref).max())


def test_multi_design_rows_independent(hemi_bem):
    """Each design's rows in a batch equal its own single-design solve
    (same bucket -> same compiled shape -> bit-identical)."""
    small = PanelBEM(hemi_mesh(npts=12, dz=0.3, da=0.8), rho=RHO, g=G)
    ka = np.array([0.8])
    w = np.sqrt(G * ka)
    both = solve_panel_batch([panels_of(hemi_bem), panels_of(small)],
                             w, ka, rho=RHO, g=G)
    for i, b in enumerate((hemi_bem, small)):
        alone = solve_panel_batch([panels_of(b)], w, ka, rho=RHO, g=G)
        np.testing.assert_array_equal(both["Abem"][i], alone["Abem"][0])
        np.testing.assert_array_equal(both["Bbem"][i], alone["Bbem"][0])
        np.testing.assert_array_equal(both["Xbre"][i], alone["Xbre"][0])


# ---------------------------------------------------------------------------
# padding exactness
# ---------------------------------------------------------------------------


def test_padded_columns_exact_zero(hemi_bem):
    pan = panels_of(hemi_bem)
    n = len(pan[0])
    Nmax = n + 37  # arbitrary padding (buckets round to 128 multiples;
    # the exactness property must hold for ANY pad amount)
    A, C, Nrm, msk, modes = bem_batch._stack_bucket([pan], Nmax)
    S, D = rankine_matrices_batch(C, A, Nrm, mode="jnp")
    S, D = np.asarray(S), np.asarray(D)
    # padded panels have zero area -> their columns are EXACT zeros
    assert np.all(S[:, :, n:] == 0.0)
    assert np.all(D[:, :, n:] == 0.0)
    # real-panel block matches the unpadded assembly bit-for-bit
    A1, C1, Nrm1, _, _ = bem_batch._stack_bucket([pan], n)
    S1, D1 = rankine_matrices_batch(C1, A1, Nrm1, mode="jnp")
    np.testing.assert_array_equal(S[:, :n, :n], np.asarray(S1))
    np.testing.assert_array_equal(D[:, :n, :n], np.asarray(D1))
    # padded modes columns are masked off
    assert np.all(np.asarray(modes)[:, :, n:] == 0.0)


def test_cross_bucket_shape_tolerance(hemi_bem, monkeypatch):
    """Results across DIFFERENT padded program shapes agree to
    reduction-order tolerance (exact bit-identity holds only at a fixed
    shape; analytically-zero couplings see ~1e-17-relative noise)."""
    ka = np.array([0.8])
    w = np.sqrt(G * ka)
    out_a = solve_panel_batch([panels_of(hemi_bem)], w, ka, rho=RHO, g=G)
    monkeypatch.setattr(bem_batch, "_BUCKET", 512)
    out_b = solve_panel_batch([panels_of(hemi_bem)], w, ka, rho=RHO, g=G)
    for key in ("Abem", "Bbem", "Xbre", "Xbim"):
        scale = np.abs(out_a[key]).max()
        np.testing.assert_allclose(out_b[key], out_a[key],
                                   rtol=1e-9, atol=1e-12 * scale)


def test_zero_panel_design_raises():
    with pytest.raises(ValueError, match="zero wetted panels"):
        solve_panel_batch(
            [(np.zeros(0), np.zeros((0, 3)), np.zeros((0, 3)))],
            np.array([1.0]), np.array([0.1]))


# ---------------------------------------------------------------------------
# fd table-cache regression (satellite: unbounded _fd_table growth)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fd_table_cache_capped(monkeypatch):
    from raft_tpu.hydro.greens_fd import wavenumber

    h = 2.0
    bem = PanelBEM(hemi_mesh(npts=10, dz=0.4, da=1.0), rho=RHO, g=G, depth=h)
    monkeypatch.setattr(PanelBEM, "_FD_CACHE_MAX", 3)
    Ks = np.linspace(0.1, 0.6, 7)
    assert all(wavenumber(K, h) * h < 6.0 for K in Ks)
    for K in Ks:
        bem._fd_table(K)
    assert len(bem._fd_tables) <= 3
    bem._fd_tables.clear()
    bem.prebuild_fd_tables(np.sqrt(G * Ks))
    assert len(bem._fd_tables) <= 3


# ---------------------------------------------------------------------------
# calcBEM parity through the design-batch entry point
# ---------------------------------------------------------------------------


def _pot_design():
    from raft_tpu.designs import demo_spar

    d = demo_spar(nw_freqs=(0.05, 0.4))
    d["platform"]["potModMaster"] = 0
    d["platform"]["members"][0]["potMod"] = True
    return d


@pytest.mark.slow
def test_solve_design_batch_matches_calcbem():
    """The stacked-variant meshing + batched solve reproduces
    fowt.calcBEM (same mesh rules, same solver) for the base design."""
    from raft_tpu.core.model import Model
    from raft_tpu.parallel.design_batch import stack_variants
    from raft_tpu.hydro.bem_batch import solve_design_batch

    d = _pot_design()
    model = Model(d)
    fowt = model.fowtList[0]
    fowt.setPosition(np.zeros(6))
    fowt.calcStatics()
    fowt.calcBEM()

    axes = [("platform.members.0.d", [d["platform"]["members"][0]["d"]])]
    stacked, treedef, _ = stack_variants(
        d, axes, [(d["platform"]["members"][0]["d"],)],
        rho=fowt.rho_water, g=fowt.g, x_ref=fowt.x_ref, y_ref=fowt.y_ref,
        heading_adjust=fowt.heading_adjust)
    out = solve_design_batch(fowt, treedef, stacked, 1,
                             np.asarray(fowt.w), np.asarray(fowt.k),
                             headings_deg=(0.0,))
    A_ref = np.moveaxis(np.asarray(fowt.A_BEM), 2, 0)
    B_ref = np.moveaxis(np.asarray(fowt.B_BEM), 2, 0)
    X_ref = np.asarray(fowt.X_BEM)  # [1,6,nw], heading-relative; 0 deg = global
    sA = max(np.abs(A_ref).max(), 1.0)
    np.testing.assert_allclose(out["Abem"][0], A_ref, atol=1e-8 * sA)
    np.testing.assert_allclose(out["Bbem"][0], B_ref, atol=1e-8 * sA)
    Xb = out["Xbre"][0] + 1j * out["Xbim"][0]
    np.testing.assert_allclose(Xb, X_ref, atol=1e-8 * np.abs(X_ref).max())


# ---------------------------------------------------------------------------
# sweep integration
# ---------------------------------------------------------------------------

_AXES = [("platform.members.0.d",
          [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5]])]
_STATES = [(4.0, 8.0), (6.0, 10.0, 30.0)]


@pytest.mark.slow
def test_sweep_potmod_end_to_end(monkeypatch):
    """potMod designs run the BATCHED path natively: no SweepAxisError
    fallback, no dropped-coefficient warning, healthy responses that
    actually carry the BEM physics (differ from the strip-only run)."""
    monkeypatch.delenv("RAFT_TPU_BEM", raising=False)
    from raft_tpu import sweep as sweep_mod

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any DROPS warning fails the test
        out = sweep_mod.sweep(_pot_design(), _AXES, _STATES, n_iter=15)
    assert np.all(out["status"] == 0)
    assert np.all(np.isfinite(out["motion_std"]))

    monkeypatch.setenv("RAFT_TPU_BEM", "off")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        out_off = sweep_mod.sweep(_pot_design(), _AXES, _STATES, n_iter=15)
    assert any("DROPS" in str(w.message) for w in rec)
    # the fallback run omits the BEM contributions -> different physics
    assert np.nanmax(np.abs(out["motion_std"] - out_off["motion_std"])) > 1e-6


@pytest.mark.slow
def test_sweep_bem_modes_agree(monkeypatch):
    """RAFT_TPU_BEM=jnp and =pallas (interpret on CPU) agree through the
    full sweep to solver tolerance."""
    from raft_tpu import sweep as sweep_mod

    monkeypatch.setenv("RAFT_TPU_BEM", "jnp")
    out_j = sweep_mod.sweep(_pot_design(), _AXES, _STATES[:1], n_iter=10)
    monkeypatch.setenv("RAFT_TPU_BEM", "pallas")
    out_p = sweep_mod.sweep(_pot_design(), _AXES, _STATES[:1], n_iter=10)
    np.testing.assert_allclose(out_p["motion_std"], out_j["motion_std"],
                               rtol=1e-8)


@pytest.mark.sentinel
def test_bem_off_sweep_zero_extra_compiles(monkeypatch):
    """Strip-theory sweeps with the tier merely AVAILABLE (the default)
    compile nothing beyond the seed programs and stay bit-identical:
    the BEM leaves extend the traced programs only when a potential-flow
    member activates the tier."""
    from raft_tpu.analysis.recompile import RecompileSentinel
    from raft_tpu.designs import demo_spar
    from raft_tpu import sweep as sweep_mod

    monkeypatch.delenv("RAFT_TPU_BEM", raising=False)
    base = demo_spar(nw_freqs=(0.05, 0.4))  # strip-only (potModMaster 1)
    warm = sweep_mod.sweep(base, _AXES, _STATES, n_iter=6)
    with RecompileSentinel() as s:
        snap = s.snapshot()
        again = sweep_mod.sweep(base, _AXES, _STATES, n_iter=6)
        s.assert_no_recompile(snap, "warm BEM-available strip sweep")
        monkeypatch.setenv("RAFT_TPU_BEM", "off")
        off = sweep_mod.sweep(base, _AXES, _STATES, n_iter=6)
        s.assert_no_recompile(snap, "warm BEM-off strip sweep")
    np.testing.assert_array_equal(warm["motion_std"], again["motion_std"])
    np.testing.assert_array_equal(warm["motion_std"], off["motion_std"])


@pytest.mark.slow
def test_sweep_bem_warm_memo(monkeypatch):
    """A repeat potMod sweep reuses the memoized BEM precompute (the
    template memo grows a 'bem' cache) and returns identical results."""
    monkeypatch.delenv("RAFT_TPU_BEM", raising=False)
    from raft_tpu import sweep as sweep_mod

    d = _pot_design()
    first = sweep_mod.sweep(d, _AXES, _STATES[:1], n_iter=10)
    memo_key = sweep_mod._template_key(d, 10, False)
    entry = sweep_mod._TEMPLATE_MEMO.get(memo_key)
    assert entry is not None and entry.get("bem"), \
        "BEM precompute was not memoized in the template memo"
    (bem_cached,) = entry["bem"].values()
    second = sweep_mod.sweep(d, _AXES, _STATES[:1], n_iter=10)
    np.testing.assert_array_equal(first["motion_std"], second["motion_std"])
    # the warm repeat reused the SAME host arrays (no re-solve)
    (bem_cached2,) = entry["bem"].values()
    assert bem_cached2 is bem_cached
