"""Native panel BEM vs the shipped OC4semi WAMIT data.

The only production-geometry potential-flow truth in the reference tree
is /root/reference/examples/OC4semi-WAMIT_Coefs/marin_semi.1 (WAMIT
added mass + radiation damping for the DeepCwind semisubmersible at
200 m depth, 498 frequencies).  This test solves the same geometry —
main column + three offset/base columns from OC4semi-WAMIT_Coefs.yaml,
meshed at the yaml's dz_BEM/da_BEM targets — with the native finite-
depth panel solver and compares against that file using the framework's
own reader conventions (A = rho*Abar, B = rho*Bbar; raft_fowt.py:742-743).

Verified accuracy at this mesh (dz=3, da=2, ~2600 wetted panels),
measured over a dense 25-frequency band sweep (0.2-1.4 rad/s):
added mass within ~5% of WAMIT on every dominant coefficient; radiation
damping within 4-14% of the local impedance scale w*A (B is far more
shape sensitive than A — the inter-column interaction peak near
w ~ 0.65 rad/s is underpredicted at this resolution, a known gap —
but at every frequency the B error stays small against the w*A term it
sits next to in Z(w)).  The bounds below codify that measured state.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

REF_YAML = "/root/reference/examples/OC4semi-WAMIT_Coefs.yaml"
REF_WAMIT = "/root/reference/examples/OC4semi-WAMIT_Coefs/marin_semi.1"

pytestmark = pytest.mark.skipif(
    not (os.path.exists(REF_YAML) and os.path.exists(REF_WAMIT)),
    reason="reference OC4semi WAMIT data not available",
)


@pytest.fixture(scope="module")
def oc4_solution():
    import yaml

    from raft_tpu.core.model import Model
    from raft_tpu.hydro import mesh as mesh_mod, wamit_io
    from raft_tpu.hydro.potential_bem import PanelBEM
    from raft_tpu.ops import waves

    with open(REF_YAML) as f:
        design = yaml.safe_load(f)
    p = design["platform"]
    # solve the potential-flow members natively instead of reading the
    # shipped coefficients: potModMaster 0 keeps the member potMod flags
    p["potModMaster"] = 0
    p.pop("hydroPath", None)
    p.pop("potSecOrder", None)
    p.pop("potFirstOrder", None)
    design.setdefault("settings", {})
    design["settings"]["min_freq"] = 0.05
    design["settings"]["max_freq"] = 0.1

    model = Model(design)
    fowt = model.fowtList[0]
    fowt.setPosition(np.zeros(6))
    mesh = mesh_mod.mesh_fowt_members(
        fowt, dz=float(p.get("dz_BEM", 3.0)), da=float(p.get("da_BEM", 2.0)))
    bem = PanelBEM(mesh, rho=fowt.rho_water, g=fowt.g, depth=200.0)

    # sample the energetic band; the .1 grid is dense (498 freqs) so
    # interpolating the reference to these points is exact to ~1e-3
    w = np.array([0.3, 0.5, 0.7, 0.9, 1.2])
    k = np.asarray(waves.wave_number(jnp.asarray(w), 200.0))
    A, B, X = bem.solve(w, k)

    Abar, Bbar, w1 = wamit_io.read_wamit1(REF_WAMIT)
    rho = fowt.rho_water
    Aref = np.zeros_like(A)
    Bref = np.zeros_like(B)
    for i in range(6):
        for j in range(6):
            Aref[i, j] = rho * np.interp(w, w1[2:], Abar[i, j, 2:])
            Bref[i, j] = rho * np.interp(w, w1[2:], Bbar[i, j, 2:])
    return w, A, B, Aref, Bref


DOMINANT = [(0, 0), (1, 1), (2, 2), (3, 3), (4, 4), (5, 5), (0, 4), (1, 3)]


def test_added_mass_vs_wamit(oc4_solution):
    w, A, B, Aref, Bref = oc4_solution
    for (i, j) in DOMINANT:
        scale = np.max(np.abs(Aref[i, j]))
        err = np.max(np.abs(A[i, j] - Aref[i, j])) / scale
        assert err < 0.06, f"A{i+1}{j+1} off WAMIT by {err:.1%}"


def test_damping_vs_wamit(oc4_solution):
    """Radiation damping against WAMIT, measured against the local
    impedance scale w*sqrt(A_ii*A_jj) it enters Z(w) next to (the
    geometric-mean form keeps the scale meaningful for coupling terms,
    whose own A_ij can pass near zero)."""
    w, A, B, Aref, Bref = oc4_solution
    for (i, j) in DOMINANT:
        scale = w * np.sqrt(np.abs(Aref[i, i]) * np.abs(Aref[j, j]))
        err = np.max(np.abs(B[i, j] - Bref[i, j]) / scale)
        assert err < 0.20, f"B{i+1}{j+1} impedance-relative error {err:.1%}"


def test_damping_positive_diagonal(oc4_solution):
    """Radiation damping must be non-negative on the diagonal (energy
    flux out of the body) at every sampled frequency."""
    w, A, B, Aref, Bref = oc4_solution
    for i in range(6):
        assert np.all(B[i, i] > -1e-3 * np.max(np.abs(B[i, i]))), f"B{i+1}{i+1} negative"
