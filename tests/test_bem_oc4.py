"""Native panel BEM vs the shipped OC4semi WAMIT data.

The only production-geometry potential-flow truth in the reference tree
is /root/reference/examples/OC4semi-WAMIT_Coefs/marin_semi.1 (WAMIT
added mass + radiation damping for the DeepCwind semisubmersible at
200 m depth, 498 frequencies).  This test solves the same geometry —
main column + three offset/base columns from OC4semi-WAMIT_Coefs.yaml,
meshed at the yaml's dz_BEM/da_BEM targets — with the native finite-
depth panel solver over a 25-frequency band (0.2-1.4 rad/s) spanning
the inter-column interaction peak near 0.65 rad/s.

WAMIT .1 normalization (important): the file stores Abar = A/(rho L^k)
and Bbar = B/(rho L^k omega) — radiation DAMPING carries an extra
1/omega.  The dimensional truth is therefore A = rho*Abar but
B = rho*omega*Bbar.  The reference's reader applies rho to both
(raft_fowt.py:742-743, `B_BEM = rho * dampingInterp`), dropping the
omega; our model-consumption path mirrors that for output parity with
the reference (see core/fowt.py), but THIS test checks the native
solver against the physical values.  (Round-5 forensics: two
independent solvers — the fast table path and the Gauss-subpanel
`bem_ref` — agreed with each other to 3% while sitting at ~55% of
rho*Bbar right where B(omega)/omega peaks; restoring the omega
collapses every channel's error to a few percent and the 0.65 rad/s
interaction peak lines up.)

Verified accuracy at this mesh (dz=3, da=2, ~2600 wetted panels),
measured over the 25-frequency sweep: added mass within 5% of WAMIT on
every dominant coefficient; radiation damping within 3.1% of the local
impedance scale w*A everywhere, and within 14% of each channel's peak
value even on the shape-sensitive heave-plate channel B33 (8.3%
elsewhere).  The bounds below codify that measured state with a small
margin.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

REF_YAML = "/root/reference/examples/OC4semi-WAMIT_Coefs.yaml"
REF_WAMIT = "/root/reference/examples/OC4semi-WAMIT_Coefs/marin_semi.1"

pytestmark = pytest.mark.skipif(
    not (os.path.exists(REF_YAML) and os.path.exists(REF_WAMIT)),
    reason="reference OC4semi WAMIT data not available",
)


@pytest.fixture(scope="module")
def oc4_solution():
    import yaml

    from raft_tpu.core.model import Model
    from raft_tpu.hydro import mesh as mesh_mod, wamit_io
    from raft_tpu.hydro.potential_bem import PanelBEM
    from raft_tpu.ops import waves

    with open(REF_YAML) as f:
        design = yaml.safe_load(f)
    p = design["platform"]
    # solve the potential-flow members natively instead of reading the
    # shipped coefficients: potModMaster 0 keeps the member potMod flags
    p["potModMaster"] = 0
    p.pop("hydroPath", None)
    p.pop("potSecOrder", None)
    p.pop("potFirstOrder", None)
    design.setdefault("settings", {})
    design["settings"]["min_freq"] = 0.05
    design["settings"]["max_freq"] = 0.1

    model = Model(design)
    fowt = model.fowtList[0]
    fowt.setPosition(np.zeros(6))
    mesh = mesh_mod.mesh_fowt_members(
        fowt, dz=float(p.get("dz_BEM", 3.0)), da=float(p.get("da_BEM", 2.0)))
    bem = PanelBEM(mesh, rho=fowt.rho_water, g=fowt.g, depth=200.0)

    # 25-frequency band across the energetic range incl. the 0.65 rad/s
    # inter-column interaction peak; the .1 grid is dense (498 freqs) so
    # interpolating the reference to these points is exact to ~1e-3
    w = np.linspace(0.2, 1.4, 25)
    k = np.asarray(waves.wave_number(jnp.asarray(w), 200.0))
    A, B, X = bem.solve(w, k)

    Abar, Bbar, w1 = wamit_io.read_wamit1(REF_WAMIT)
    rho = fowt.rho_water
    Aref = np.zeros_like(A)
    Bref = np.zeros_like(B)
    for i in range(6):
        for j in range(6):
            Aref[i, j] = rho * np.interp(w, w1[2:], Abar[i, j, 2:])
            # dimensional damping: B = rho * omega * Bbar (WAMIT .1
            # convention; see module docstring)
            Bref[i, j] = rho * w * np.interp(w, w1[2:], Bbar[i, j, 2:])
    return w, A, B, Aref, Bref


DOMINANT = [(0, 0), (1, 1), (2, 2), (3, 3), (4, 4), (5, 5), (0, 4), (1, 3)]


def test_added_mass_vs_wamit(oc4_solution):
    w, A, B, Aref, Bref = oc4_solution
    for (i, j) in DOMINANT:
        scale = np.max(np.abs(Aref[i, j]))
        err = np.max(np.abs(A[i, j] - Aref[i, j])) / scale
        assert err < 0.06, f"A{i+1}{j+1} off WAMIT by {err:.1%}"


def test_damping_vs_wamit(oc4_solution):
    """Radiation damping against WAMIT (dimensional, B = rho*w*Bbar):
    within 5% of the local impedance scale w*sqrt(A_ii*A_jj) it enters
    Z(w) next to, at every one of the 25 frequencies."""
    w, A, B, Aref, Bref = oc4_solution
    for (i, j) in DOMINANT:
        scale = w * np.sqrt(np.abs(Aref[i, i]) * np.abs(Aref[j, j]))
        err = np.max(np.abs(B[i, j] - Bref[i, j]) / scale)
        assert err < 0.05, f"B{i+1}{j+1} impedance-relative error {err:.1%}"


def test_damping_peak_shape(oc4_solution):
    """Each dominant damping channel tracks WAMIT's curve relative to its
    own peak — this pins the 0.65 rad/s inter-column interaction peak's
    presence, location, and height (a missing or shifted peak shows up
    as an O(1) fraction-of-peak error)."""
    w, A, B, Aref, Bref = oc4_solution
    for (i, j) in DOMINANT:
        peak = np.max(np.abs(Bref[i, j]))
        err = np.max(np.abs(B[i, j] - Bref[i, j])) / peak
        tol = 0.16 if (i, j) == (2, 2) else 0.10  # heave plates: shape-sensitive
        assert err < tol, f"B{i+1}{j+1} rel-to-peak error {err:.1%}"


def test_damping_positive_diagonal(oc4_solution):
    """Radiation damping must be non-negative on the diagonal (energy
    flux out of the body) at every sampled frequency.  Tolerance: the
    semisub's heave damping has a physical near-zero minimum (wave
    cancellation between columns and plates ~0.45 rad/s) where the
    numerics may dip to a few 0.1% of the channel peak."""
    w, A, B, Aref, Bref = oc4_solution
    for i in range(6):
        assert np.all(B[i, i] > -3e-3 * np.max(np.abs(B[i, i]))), f"B{i+1}{i+1} negative"
