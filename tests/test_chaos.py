"""Chaos harness, watchdog, graceful shutdown and elastic re-mesh.

The robustness layer's contract is the executor's, extended to
failures: injected faults change RECOVERY PATHS, never results.  Every
test that survives a fault asserts bit-identity against the clean
sweep — same dtypes, same health and status arrays — and the chaos-off
path is sentinel-pinned to zero extra XLA compiles, so the whole layer
is provably free when disarmed.

Faults are injected through ``raft_tpu.robust.chaos`` specs
(deterministically seeded, so every failure here replays exactly);
the recovery machinery under test lives in ``raft_tpu.robust.elastic``
and the seams threaded through ``raft_tpu.sweep``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax

from raft_tpu import config as _config
from raft_tpu import sweep as sweep_mod
from raft_tpu.designs import demo_spar
from raft_tpu.obs import ledger as obs_ledger
from raft_tpu.obs import live
from raft_tpu.parallel.executor import ChunkTimeout, ChunkTimer, \
    call_with_deadline
from raft_tpu.robust import STATUS_OK
from raft_tpu.robust import chaos as chaos_mod
from raft_tpu.robust import elastic
from raft_tpu.robust import quarantine
from raft_tpu.sweep import sweep

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5],
          [9.0, 9.0, 6.5, 6.5], [9.6, 9.6, 6.5, 6.5],
          [10.2, 10.2, 6.5, 6.5], [10.8, 10.8, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]

RESULT_KEYS = ("motion_std", "AxRNA_std", "mass", "displacement", "GMT",
               "status")


def _sweep(**kw):
    kw.setdefault("n_iter", 8)
    kw.setdefault("chunk_size", 2)
    return sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES, **kw)


def _assert_bit_identical(a, b):
    for k in RESULT_KEYS:
        x, y = np.asarray(a[k]), np.asarray(b[k])
        assert x.dtype == y.dtype, (k, x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y, err_msg=k)
    for k in a["health"]:
        x, y = np.asarray(a["health"][k]), np.asarray(b["health"][k])
        assert x.dtype == y.dtype, (f"health.{k}", x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y, err_msg=f"health.{k}")


def _ledger_sweep(tmp_path, monkeypatch, name, **kw):
    ldir = tmp_path / name
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    out = _sweep(**kw)
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    runs = obs_ledger.list_runs(str(ldir))
    assert len(runs) == 1, runs
    return out, obs_ledger.read_events(runs[0])


@pytest.fixture(scope="module")
def base():
    out = _sweep()
    assert (out["status"] == STATUS_OK).all()
    return out


# ---------------------------------------------------------------------------
# chaos spec grammar + deterministic rolls
# ---------------------------------------------------------------------------


def test_parse_spec_grammar():
    rules = chaos_mod.parse_spec(
        "hang:chunk=2,secs=5; poison_fetch:p=0.25 ;device_lost:device=3,n=2")
    assert [r.seam for r in rules] == ["hang", "poison_fetch", "device_lost"]
    hang, poison, lost = rules
    assert hang.chunk == 2 and hang.secs == 5.0
    # chunk-targeted rules default to a single fire; free rules don't
    assert hang.n == 1 and poison.n is None
    assert poison.p == 0.25 and poison.chunk is None
    assert lost.device == 3 and lost.n == 2
    assert chaos_mod.parse_spec("") == []
    assert chaos_mod.parse_spec("  ;  ") == []


def test_parse_spec_rejects_unknowns():
    with pytest.raises(ValueError, match="unknown chaos seam"):
        chaos_mod.parse_spec("gremlin:p=1")
    with pytest.raises(ValueError, match="bad chaos rule argument"):
        chaos_mod.parse_spec("hang:volume=11")
    with pytest.raises(ValueError, match="bad chaos rule argument"):
        chaos_mod.parse_spec("hang:chunk")


def test_roll_determinism():
    a = chaos_mod._roll(7, "fp", "poison_fetch", 3)
    b = chaos_mod._roll(7, "fp", "poison_fetch", 3)
    assert a == b and 0.0 <= a < 1.0
    assert a != chaos_mod._roll(7, "fp", "poison_fetch", 4)
    assert a != chaos_mod._roll(8, "fp", "poison_fetch", 3)
    assert a != chaos_mod._roll(7, "fq", "poison_fetch", 3)


def test_chaos_plan_budget_and_device_filter():
    plan = chaos_mod.ChaosPlan("poison_fetch:p=1,n=2")
    assert plan.seams == ("poison_fetch",)
    assert plan.fires("poison_fetch") is not None
    assert plan.fires("poison_fetch") is not None
    assert plan.fires("poison_fetch") is None          # budget exhausted
    assert plan.fires("hang") is None                  # no rule for the seam

    plan = chaos_mod.ChaosPlan("device_lost:chunk=1,device=3")
    # the named device is not in the mesh -> rule is skipped, budget kept
    assert plan.fires("device_lost", key=1, device_ids=[0, 1, 2]) is None
    with pytest.raises(chaos_mod.ChaosDeviceLost) as ei:
        plan.maybe_raise("device_lost", chunk=1, device_ids=[0, 1, 2, 3])
    assert ei.value.device_id == 3
    # chunk-targeted default budget n=1: the retry goes through clean
    plan.maybe_raise("device_lost", chunk=1, device_ids=[0, 1, 2, 3])


def test_chaos_config_env(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_CHAOS", raising=False)
    monkeypatch.delenv("RAFT_TPU_CHAOS_SEED", raising=False)
    assert _config.chaos_config() == {"spec": "", "seed": 0}
    monkeypatch.setenv("RAFT_TPU_CHAOS", " hang:chunk=0 ")
    monkeypatch.setenv("RAFT_TPU_CHAOS_SEED", "11")
    cfg = _config.chaos_config()
    assert cfg == {"spec": "hang:chunk=0", "seed": 11}
    assert _config.chaos_config({"seed": 5})["seed"] == 5
    with pytest.raises(ValueError, match="unknown"):
        _config.chaos_config({"bogus": 1})


def test_resilience_config_env(monkeypatch):
    for var in ("RAFT_TPU_WATCHDOG", "RAFT_TPU_WATCHDOG_FLOOR",
                "RAFT_TPU_RETRY_BACKOFF", "RAFT_TPU_GRACEFUL",
                "RAFT_TPU_REMESH"):
        monkeypatch.delenv(var, raising=False)
    cfg = _config.resilience_config()
    assert cfg["watchdog"] is False and cfg["graceful"] == "term"
    assert cfg["remesh"] is True and cfg["retry_backoff_s"] == 0.0
    monkeypatch.setenv("RAFT_TPU_WATCHDOG", "1")
    monkeypatch.setenv("RAFT_TPU_WATCHDOG_FLOOR", "2.5")
    monkeypatch.setenv("RAFT_TPU_RETRY_BACKOFF", "0.125")
    monkeypatch.setenv("RAFT_TPU_GRACEFUL", "all")
    monkeypatch.setenv("RAFT_TPU_REMESH", "0")
    cfg = _config.resilience_config()
    assert cfg["watchdog"] is True and cfg["watchdog_floor_s"] == 2.5
    assert cfg["retry_backoff_s"] == 0.125 and cfg["graceful"] == "all"
    assert cfg["remesh"] is False
    monkeypatch.setenv("RAFT_TPU_GRACEFUL", "sometimes")
    with pytest.raises(ValueError, match="RAFT_TPU_GRACEFUL"):
        _config.resilience_config()


# ---------------------------------------------------------------------------
# watchdog primitives + retry backoff (unit)
# ---------------------------------------------------------------------------


def test_chunk_timer_deadlines():
    timer = ChunkTimer(floor_s=1.0, mult=4.0, cold_s=7.0)
    assert timer.deadline() == 7.0                # cold: no observations
    for s in (0.5, 0.7, 0.6):
        timer.observe(s)
    assert timer.deadline() == pytest.approx(4.0 * 0.6)
    for _ in range(5):
        timer.observe(0.001)                      # median shifts to 1ms
    assert timer.deadline() == 1.0                # floored
    for _ in range(2 * ChunkTimer.WINDOW):
        timer.observe(9.0)                        # window slides
    assert timer.deadline() == pytest.approx(36.0)


def test_call_with_deadline():
    assert call_with_deadline(lambda: 42, 5.0) == 42
    with pytest.raises(ValueError, match="boom"):
        call_with_deadline(lambda: (_ for _ in ()).throw(ValueError("boom")),
                           5.0)
    release = threading.Event()
    with pytest.raises(ChunkTimeout, match=r"deadline"):
        call_with_deadline(lambda: release.wait(30), 0.05, what="chunk 9")
    release.set()                                 # unblock the worker


def test_backoff_delay_deterministic_and_capped():
    d0 = quarantine._backoff_delay(0.1, 30.0, idx=np.arange(4), attempt=0)
    assert d0 == quarantine._backoff_delay(0.1, 30.0, idx=np.arange(4),
                                           attempt=0)
    d1 = quarantine._backoff_delay(0.1, 30.0, idx=np.arange(4), attempt=1)
    # exponential growth with bounded jitter: base*2^a <= d < 1.5*base*2^a
    assert 0.1 <= d0 < 0.15 and 0.2 <= d1 < 0.3
    assert quarantine._backoff_delay(0.0, 30.0, idx=np.arange(4),
                                     attempt=3) == 0.0
    assert quarantine._backoff_delay(10.0, 0.5, idx=np.arange(4),
                                     attempt=5) == 0.5   # capped
    # jitter depends on the quarantined row set
    assert d0 != quarantine._backoff_delay(0.1, 30.0, idx=np.arange(5),
                                           attempt=0)


def test_shutdown_guard_install_and_restore():
    prev = signal.getsignal(signal.SIGTERM)
    with elastic.ShutdownGuard(mode="term") as g:
        assert g.installed and not g.stop_requested
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)                          # let the handler run
        assert g.stop_requested and g.signal_name == "SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is prev

    with elastic.ShutdownGuard(mode="off") as g:
        assert not g.installed

    box = {}

    def _worker():
        with elastic.ShutdownGuard(mode="term") as g:
            box["installed"] = g.installed

    t = threading.Thread(target=_worker)
    t.start()
    t.join()
    assert box["installed"] is False              # signals need main thread


# ---------------------------------------------------------------------------
# chaos-off: the robustness layer is provably free
# ---------------------------------------------------------------------------


@pytest.mark.sentinel
def test_chaos_off_bit_identity_zero_compiles(base, monkeypatch):
    from raft_tpu.analysis.recompile import RecompileSentinel

    with RecompileSentinel() as s:
        snap = s.snapshot()
        repeat = _sweep(chaos=False)
        s.assert_no_recompile(snap, "chaos-off sweep")
        _assert_bit_identical(base, repeat)

        # watchdog + backoff + graceful armed: still zero traced changes
        monkeypatch.setenv("RAFT_TPU_WATCHDOG", "1")
        monkeypatch.setenv("RAFT_TPU_RETRY_BACKOFF", "0.01")
        monkeypatch.setenv("RAFT_TPU_GRACEFUL", "all")
        guarded = _sweep()
        s.assert_no_recompile(snap, "watchdog-armed sweep")
        _assert_bit_identical(base, guarded)


# ---------------------------------------------------------------------------
# fault seams end-to-end (each recovers bit-identical)
# ---------------------------------------------------------------------------


def test_poison_fetch_quarantine_recovery(base, tmp_path, monkeypatch):
    with pytest.warns(RuntimeWarning, match="isolating faults"):
        out, events = _ledger_sweep(tmp_path, monkeypatch, "poison",
                                    chaos="poison_fetch:chunk=1")
    _assert_bit_identical(base, out)
    injects = [e for e in events if e["event"] == "chaos_inject"]
    assert injects and injects[0]["seam"] == "poison_fetch"
    assert injects[0]["chunk"] == 1
    faults = [e for e in events if e["event"] == "chunk_fault"]
    assert faults and "poison_fetch" in faults[0]["error"]
    assert events[-1]["event"] == "run_end" and events[-1]["ok"] is True


def test_retry_backoff_emitted_on_quarantine_retry(base, tmp_path,
                                                   monkeypatch):
    # a transient fault that reproduces exactly once under isolation:
    # the quarantine retry succeeds after one deterministic backoff
    monkeypatch.setenv("RAFT_TPU_RETRY_BACKOFF", "0.01")
    fails = {"n": 0}

    def hook(idx, dispatch):
        if (np.asarray(idx) == 2).any() and fails["n"] < 2:
            fails["n"] += 1
            raise RuntimeError("transient fault")
        return dispatch(idx)

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    with pytest.warns(RuntimeWarning, match="isolating faults"):
        out, events = _ledger_sweep(tmp_path, monkeypatch, "backoff")
    _assert_bit_identical(base, out)
    retries = [e for e in events if e["event"] == "quarantine_retry"]
    assert len(retries) == 1
    expect = quarantine._backoff_delay(0.01, 30.0, np.arange(2, 4), 0)
    assert retries[0]["backoff_s"] == pytest.approx(round(expect, 6))
    assert 0.01 <= retries[0]["backoff_s"] < 0.015


def test_hang_watchdog_timeout(base, tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_WATCHDOG", "1")
    monkeypatch.setenv("RAFT_TPU_WATCHDOG_FLOOR", "0.3")
    monkeypatch.setenv("RAFT_TPU_WATCHDOG_COLD", "1.0")
    with pytest.warns(RuntimeWarning, match="ChunkTimeout"):
        out, events = _ledger_sweep(tmp_path, monkeypatch, "hang",
                                    chaos="hang:chunk=0,secs=10")
    _assert_bit_identical(base, out)
    timeouts = [e for e in events if e["event"] == "chunk_timeout"]
    assert timeouts and timeouts[0]["chunk"] == 0
    assert timeouts[0]["deadline_s"] <= 1.0 + 1e-9
    assert not elastic.deadline_exceeded()        # cleared on recovery
    assert events[-1]["event"] == "run_end" and events[-1]["ok"] is True


@pytest.mark.slow
def test_device_lost_elastic_remesh(base, tmp_path, monkeypatch):
    # slow: compiles executables for two fresh mesh topologies (4- and
    # 3-device); the chaos CI job runs it, tier-1 skips it
    devs = jax.devices()[:4]
    # 8 designs / 4-way design axis: global chunk covers the whole sweep,
    # so the only pipeline chunk is 0
    with pytest.warns(RuntimeWarning, match="re-meshing"):
        out, events = _ledger_sweep(tmp_path, monkeypatch, "lost",
                                    devices=devs,
                                    chaos="device_lost:chunk=0,device=3")
    _assert_bit_identical(base, out)
    lost = [e for e in events if e["event"] == "device_lost"]
    assert lost and "device lost" in lost[0]["error"]
    remesh = [e for e in events if e["event"] == "remesh"]
    assert remesh
    assert 3 in remesh[0]["from_devices"]
    assert 3 not in remesh[0]["to_devices"]
    assert len(remesh[0]["to_devices"]) == 3
    assert events[-1]["event"] == "run_end" and events[-1]["ok"] is True


@pytest.mark.sentinel
def test_preempt_graceful_drain_and_resume(base, tmp_path, monkeypatch):
    from raft_tpu.analysis.recompile import RecompileSentinel

    ck = tmp_path / "preempt.npz"
    ldir = tmp_path / "preempt-ledger"
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    with pytest.raises(elastic.SweepPreempted, match="resumable checkpoint"):
        _sweep(checkpoint=str(ck), chaos="preempt:chunk=1")
    monkeypatch.delenv("RAFT_TPU_LEDGER")

    events = obs_ledger.read_events(obs_ledger.list_runs(str(ldir))[0])
    pre = [e for e in events if e["event"] == "preempt"]
    assert pre and pre[0]["signal"] == "SIGTERM"
    assert pre[0]["checkpoint"] == str(ck)
    end = events[-1]
    assert end["event"] == "run_end" and end["ok"] is False
    assert end["reason"] == "preempted"

    with np.load(str(ck), allow_pickle=False) as dat:
        n_done = int(dat["done"].sum())
    assert 0 < n_done < 8                          # a real mid-sweep drain

    # resume is warm: bit-identical with zero extra XLA compiles
    with RecompileSentinel() as s:
        snap = s.snapshot()
        out = _sweep(checkpoint=str(ck))
        s.assert_no_recompile(snap, "preempt resume")
    _assert_bit_identical(base, out)


def test_ckpt_fail_keeps_results(base, tmp_path, monkeypatch):
    ck = tmp_path / "doomed.npz"
    with pytest.warns(RuntimeWarning,
                      match="background checkpoint write failed"):
        out, events = _ledger_sweep(tmp_path, monkeypatch, "ckptfail",
                                    checkpoint=str(ck),
                                    chaos="ckpt_fail:p=1,n=99")
    _assert_bit_identical(base, out)
    assert not ck.exists()                        # never half-written
    assert not list(tmp_path.glob("doomed.npz.*.tmp.npz"))
    flush = [e for e in events if e["event"] == "checkpoint_flush"]
    assert flush and not any(e["ok"] for e in flush)


def test_checkpoint_atomic_corrupt_tail_and_stale_tmp(base, tmp_path):
    ck = tmp_path / "resume.npz"
    out = _sweep(checkpoint=str(ck))
    _assert_bit_identical(base, out)
    size = ck.stat().st_size

    # corrupt tail (killed mid-write without the atomic rename): the
    # resume warns, starts fresh, and repairs the file
    ck.write_bytes(ck.read_bytes()[: size // 2])
    stale = tmp_path / f"resume.npz.{os.getpid() + 1}.tmp.npz"
    stale.write_bytes(b"orphaned partial")
    with pytest.warns(RuntimeWarning, match="unreadable"):
        out2 = _sweep(checkpoint=str(ck))
    _assert_bit_identical(base, out2)
    assert not stale.exists()                     # stale tmp swept on entry
    with np.load(str(ck), allow_pickle=False) as dat:
        assert dat["done"].all()                  # repaired + complete


def test_oom_upload_host_packing_fallback(base, tmp_path, monkeypatch):
    # drop the memoized resident batch so the upload seam re-runs
    for entry in sweep_mod._TEMPLATE_MEMO.values():
        entry.pop("resident", None)
    with pytest.warns(RuntimeWarning, match="per-chunk host packing"):
        out, events = _ledger_sweep(tmp_path, monkeypatch, "oom",
                                    chaos="oom_upload:p=1")
    _assert_bit_identical(base, out)
    falls = [e for e in events if e["event"] == "capability_fallback"]
    assert falls and falls[0]["reason"] == "resident_oom"
    assert events[-1]["event"] == "run_end" and events[-1]["ok"] is True


# ---------------------------------------------------------------------------
# live endpoint: /healthz + port-in-use fallback
# ---------------------------------------------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_healthz_reflects_watchdog(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_METRICS_PORT", "0")
    live.stop_server()
    try:
        srv = live.ensure_server()
        assert srv is not None
        code, body = _get(srv.url + "/healthz")
        assert code == 200 and body["ok"] is True
        elastic._set_overdue(True)
        code, body = _get(srv.url + "/healthz")
        assert code == 503 and body["watchdog_overdue"] is True
        elastic._set_overdue(False)
        code, body = _get(srv.url + "/healthz")
        assert code == 200
    finally:
        elastic._set_overdue(False)
        live.stop_server()


def test_live_port_in_use_falls_back(monkeypatch):
    blocker = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    taken = blocker.getsockname()[1]
    monkeypatch.setenv("RAFT_TPU_METRICS_PORT", str(taken))
    live.stop_server()
    try:
        srv = live.ensure_server()
        assert srv is not None and srv.port != taken
        code, _ = _get(srv.url + "/healthz")
        assert code == 200
    finally:
        live.stop_server()
        blocker.close()


# ---------------------------------------------------------------------------
# crash-resume exactness: SIGTERM a real subprocess at a chunk boundary
# ---------------------------------------------------------------------------

_CHILD = """\
import sys

from raft_tpu import config as _config
_config.force_host_mesh(8)
_config.enable_x64()

import numpy as np
from raft_tpu.designs import demo_spar
from raft_tpu.robust.elastic import SweepPreempted
from raft_tpu.sweep import sweep

mode, ckpt, out_npz = sys.argv[1], sys.argv[2], sys.argv[3]
AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5],
          [9.0, 9.0, 6.5, 6.5], [9.6, 9.6, 6.5, 6.5]])]
chaos = "preempt:chunk=1" if mode == "interrupt" else False
try:
    out = sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, [(4.0, 8.0)],
                n_iter=6, chunk_size=2, checkpoint=ckpt or None, chaos=chaos)
except SweepPreempted:
    sys.exit(43)
np.savez(out_npz, **{k: np.asarray(out[k])
                     for k in ("motion_std", "mass", "status")})
"""


@pytest.mark.slow
def test_subprocess_sigterm_resume_bit_identical(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(
        sweep_mod.__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RAFT_TPU_EXEC_CACHE=str(tmp_path / "xcache"),
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("RAFT_TPU_CHAOS", None)

    def _run(mode, ckpt, out_npz):
        return subprocess.run(
            [sys.executable, str(script), mode, ckpt, str(out_npz)],
            env=env, capture_output=True, text=True, timeout=900)

    clean = _run("clean", "", tmp_path / "clean.npz")
    assert clean.returncode == 0, clean.stderr[-2000:]

    ck = str(tmp_path / "ck.npz")
    hit = _run("interrupt", ck, tmp_path / "na.npz")
    assert hit.returncode == 43, (hit.returncode, hit.stderr[-2000:])
    with np.load(ck, allow_pickle=False) as dat:
        assert 0 < int(dat["done"].sum()) < 6
    resumed = _run("resume", ck, tmp_path / "resumed.npz")
    assert resumed.returncode == 0, resumed.stderr[-2000:]

    with np.load(tmp_path / "clean.npz") as a, \
            np.load(tmp_path / "resumed.npz") as b:
        for k in ("motion_std", "mass", "status"):
            assert a[k].dtype == b[k].dtype
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


# ---------------------------------------------------------------------------
# compile-service crash (LAST: clears the template memo -> cold compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_compile_crash_inline_jit_fallback(base, tmp_path, monkeypatch):
    # slow: clears the template memo to force a cold AOT path (the
    # compile-service seam only fires on real compiles)
    sweep_mod._TEMPLATE_MEMO.clear()
    out, events = _ledger_sweep(tmp_path, monkeypatch, "ccrash",
                                chaos="compile_crash:p=1,n=2")
    _assert_bit_identical(base, out)
    injects = [e for e in events if e["event"] == "chaos_inject"
               and e["seam"] == "compile_crash"]
    assert len(injects) == 2
    assert events[-1]["event"] == "run_end" and events[-1]["ok"] is True
