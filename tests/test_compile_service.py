"""Background compile pipeline + serialized-executable cache
(raft_tpu.parallel.compile_service, sweep.precompile).

Three contracts under test:

* the serialized-executable cache changes WHERE executables come from,
  never what they compute: a warm exec-cache sweep performs ZERO real
  XLA compiles (RecompileSentinel + ledger both attest) while staying
  bit-identical to the freshly-compiled path, and every unusable entry
  (corrupt, truncated, wrong jax version) is rejected with an
  ``exec_cache_reject`` event and falls back to a clean fresh compile;
* the compile service overlaps XLA with host work: with a fault-injected
  slow compile, the host plan phases provably run while the compiles are
  pending, and the ledger's ``compile_overlap`` accounting matches the
  profiling phase stats at the first-dispatch join;
* none of the knobs change results: compile service on/off and pipeline
  depth 1 vs 3 are bit-identical.
"""

import os
import pickle
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu import profiling
from raft_tpu import sweep as sweep_mod
from raft_tpu.config import compile_config
from raft_tpu.designs import demo_spar
from raft_tpu.obs import ledger as obs_ledger
from raft_tpu.parallel import compile_service as cs

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]


def _sweep(**kw):
    kw.setdefault("n_iter", 8)
    kw.setdefault("chunk_size", 2)
    return sweep_mod.sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES, **kw)


def _assert_same_results(a, b):
    np.testing.assert_array_equal(a["motion_std"], b["motion_std"])
    np.testing.assert_array_equal(a["AxRNA_std"], b["AxRNA_std"])
    np.testing.assert_array_equal(a["status"], b["status"])
    for k in ("mass", "displacement", "GMT"):
        np.testing.assert_array_equal(a[k], b[k])


def _ledger_sweep(tmp_path, monkeypatch, name, **kw):
    ldir = tmp_path / name
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    out = _sweep(**kw)
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    runs = obs_ledger.list_runs(str(ldir))
    assert len(runs) == 1, runs
    return out, obs_ledger.read_events(runs[0])


def _by(events):
    by = {}
    for ev in events:
        by.setdefault(ev["event"], []).append(ev)
    return by


@pytest.fixture(scope="module")
def exec_cache(tmp_path_factory):
    """One serialized-executable cache directory shared by the sweep
    tests in this module: the first cold sweep populates it, later
    tests deserialize from it (cheap) instead of recompiling."""
    return str(tmp_path_factory.mktemp("exec-cache"))


@pytest.fixture(scope="module")
def baseline():
    """Reference sweep output, freshly compiled WITHOUT the exec cache
    (the bit-identity anchor for every cached/knob variant)."""
    old = os.environ.pop("RAFT_TPU_EXEC_CACHE", None)
    try:
        return _sweep()
    finally:
        if old is not None:
            os.environ["RAFT_TPU_EXEC_CACHE"] = old


# ---------------------------------------------------------------------------
# config + unit-level cache behavior (tiny programs, no sweep)
# ---------------------------------------------------------------------------


def test_compile_config_defaults_and_env(monkeypatch):
    for var in ("RAFT_TPU_COMPILE_SERVICE", "RAFT_TPU_COMPILE_WORKERS",
                "RAFT_TPU_EXEC_CACHE"):
        monkeypatch.delenv(var, raising=False)
    assert compile_config() == {"service": True, "workers": 2,
                                "exec_cache": None}
    monkeypatch.setenv("RAFT_TPU_COMPILE_SERVICE", "0")
    monkeypatch.setenv("RAFT_TPU_COMPILE_WORKERS", "7")
    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE", "/tmp/x")
    cfg = compile_config()
    assert cfg == {"service": False, "workers": 7, "exec_cache": "/tmp/x"}
    # workers floors at 1; empty cache path means disabled
    monkeypatch.setenv("RAFT_TPU_COMPILE_WORKERS", "0")
    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE", "")
    cfg = compile_config()
    assert cfg["workers"] == 1 and cfg["exec_cache"] is None
    # explicit overrides beat the environment; unknown keys raise
    assert compile_config({"service": True})["service"] is True
    with pytest.raises(ValueError, match="unknown compile config"):
        compile_config({"workres": 2})


def _lowered_unit_fn():
    def unit_fn(x):
        return jnp.sin(x) * 2.0 + 1.0

    return jax.jit(unit_fn).lower(
        jax.ShapeDtypeStruct((8,), jnp.float32))


def _unit_run(tmp_path, monkeypatch, name):
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path / name))
    return obs_ledger.start_run(name)


def test_exec_cache_roundtrip_bit_identical(tmp_path, monkeypatch):
    """serialize -> deserialize produces an executable whose output is
    bit-identical to the freshly compiled one, with the full
    miss/store/hit event story."""
    cache = str(tmp_path / "cache")
    cfg = {"exec_cache": cache, "service": False}
    x = jnp.arange(8, dtype=jnp.float32)
    run = _unit_run(tmp_path, monkeypatch, "roundtrip")

    cold = cs.CompileService(run=run, config=cfg).submit(
        "U", _lowered_unit_fn(), cache_tag="unit")
    assert not cold.pending and cold.source == "compile"
    want = np.asarray(cold.result(x))

    warm = cs.CompileService(run=run, config=cfg).submit(
        "U", _lowered_unit_fn(), cache_tag="unit")
    assert warm.source == "exec_cache"
    np.testing.assert_array_equal(np.asarray(warm.result(x)), want)

    run.finish(ok=True)
    by = _by(obs_ledger.read_events(run.path))
    assert len(by["exec_cache_miss"]) == 1
    assert len(by["exec_cache_store"]) == 1 and by["exec_cache_store"][0]["bytes"] > 0
    assert len(by["exec_cache_hit"]) == 1
    # only the cold build was a real compile
    assert [e.get("real") for e in by["compile_start"]] == [True]
    # a different cache tag is a different entry (no false sharing)
    other = cs.CompileService(run=obs_ledger.NULL_RUN, config=cfg).submit(
        "U", _lowered_unit_fn(), cache_tag="other-tag")
    assert other.source == "compile"


def test_corrupt_and_truncated_entries_fall_back(tmp_path, monkeypatch):
    """Garbage or truncated cache files are rejected (with the reason)
    and the build falls back to a clean fresh compile that REPAIRS the
    entry."""
    cache = str(tmp_path / "cache")
    cfg = {"exec_cache": cache, "service": False}
    x = jnp.arange(8, dtype=jnp.float32)
    svc = cs.CompileService(run=obs_ledger.NULL_RUN, config=cfg)
    want = np.asarray(svc.submit("U", _lowered_unit_fn(),
                                 cache_tag="unit").result(x))
    entry, = [os.path.join(cache, f) for f in os.listdir(cache)
              if f.endswith(".jexec")]

    for label, corruption in [
            ("garbage", lambda raw: b"not a pickle at all"),
            ("truncated", lambda raw: raw[: len(raw) // 2])]:
        with open(entry, "rb") as fh:
            raw = fh.read()
        with open(entry, "wb") as fh:
            fh.write(corruption(raw))
        run = _unit_run(tmp_path, monkeypatch, f"corrupt-{label}")
        task = cs.CompileService(run=run, config=cfg).submit(
            "U", _lowered_unit_fn(), cache_tag="unit")
        assert task.source == "compile", label
        np.testing.assert_array_equal(np.asarray(task.result(x)), want)
        run.finish(ok=True)
        by = _by(obs_ledger.read_events(run.path))
        rejects = by["exec_cache_reject"]
        assert len(rejects) == 1 and rejects[0]["key"] == "U"
        # the fresh compile re-stored a good entry
        assert len(by["exec_cache_store"]) == 1
        warm = cs.CompileService(run=obs_ledger.NULL_RUN, config=cfg).submit(
            "U", _lowered_unit_fn(), cache_tag="unit")
        assert warm.source == "exec_cache", label


def test_jax_version_mismatch_rejected(tmp_path, monkeypatch):
    """An entry written by a different jax version must NOT be loaded:
    rejected with an exec_cache_reject naming the mismatch."""
    cache = str(tmp_path / "cache")
    cfg = {"exec_cache": cache, "service": False}
    svc = cs.CompileService(run=obs_ledger.NULL_RUN, config=cfg)
    svc.submit("U", _lowered_unit_fn(), cache_tag="unit")
    entry, = [os.path.join(cache, f) for f in os.listdir(cache)
              if f.endswith(".jexec")]
    with open(entry, "rb") as fh:
        payload = pickle.load(fh)
    payload["meta"]["jax"] = "0.0.1-not-this-one"
    with open(entry, "wb") as fh:
        pickle.dump(payload, fh)

    run = _unit_run(tmp_path, monkeypatch, "vermismatch")
    task = cs.CompileService(run=run, config=cfg).submit(
        "U", _lowered_unit_fn(), cache_tag="unit")
    assert task.source == "compile"
    run.finish(ok=True)
    by = _by(obs_ledger.read_events(run.path))
    reject, = by["exec_cache_reject"]
    assert "jax mismatch" in reject["reason"]
    assert "0.0.1-not-this-one" in reject["reason"]


def test_backend_pin_mismatch_warns_once(tmp_path, monkeypatch, caplog):
    """A cache populated by a different backend warns ONCE through the
    obs/log funnel (NOT warnings.warn) instead of silently missing —
    and enable_compilation_cache() runs the same check (the two caches
    must compose visibly)."""
    import logging
    import warnings as warnings_mod

    from raft_tpu.config import enable_compilation_cache

    cache = str(tmp_path / "pinned")
    os.makedirs(cache)
    with open(os.path.join(cache, "BACKEND"), "w") as fh:
        fh.write("definitely-not-this-backend\n")
    assert cs.exec_cache_backend_pin(cache) == "definitely-not-this-backend"

    with caplog.at_level(logging.WARNING, logger="raft_tpu.parallel.compile_service"):
        with warnings_mod.catch_warnings():
            warnings_mod.simplefilter("error")  # warnings.warn would raise
            assert cs.warn_if_backend_mismatch(cache) == (
                "definitely-not-this-backend", jax.default_backend())
            # second call: still reports the mismatch, does not re-log
            cs.warn_if_backend_mismatch(cache)
            # the persistent-XLA-cache entry point runs the same check
            monkeypatch.setenv("RAFT_TPU_EXEC_CACHE", cache)
            enable_compilation_cache()
    hits = [r for r in caplog.records if "pinned to backend" in r.getMessage()]
    assert len(hits) == 1
    # a matching pin stays silent
    ok_cache = str(tmp_path / "ok")
    os.makedirs(ok_cache)
    with open(os.path.join(ok_cache, "BACKEND"), "w") as fh:
        fh.write(jax.default_backend() + "\n")
    assert cs.warn_if_backend_mismatch(ok_cache) is None


# ---------------------------------------------------------------------------
# sweep-level: zero-compile warm starts (the acceptance gate)
# ---------------------------------------------------------------------------


@pytest.mark.sentinel
def test_warm_exec_cache_sweep_zero_real_compiles(
        tmp_path, monkeypatch, exec_cache, baseline):
    """ISSUE acceptance: with RAFT_TPU_EXEC_CACHE warm, a cold-memo
    sweep (fresh-process simulation) performs ZERO real XLA compiles —
    RecompileSentinel and the ledger both attest — and its results are
    bit-identical to the uncached freshly-compiled path."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE", exec_cache)

    # cold: populates the cache (real compiles, stores)
    sweep_mod._TEMPLATE_MEMO.clear()
    cold, cold_events = _ledger_sweep(tmp_path, monkeypatch, "cold")
    cold_by = _by(cold_events)
    assert {e["key"] for e in cold_by["exec_cache_store"]} == {"A", "B"}
    assert [f for f in os.listdir(exec_cache) if f.endswith(".jexec")]
    _assert_same_results(baseline, cold)

    # warm: a fresh process would start exactly here — no template memo,
    # only the on-disk executables
    sweep_mod._TEMPLATE_MEMO.clear()
    with RecompileSentinel() as s:
        snap = s.snapshot()
        warm, warm_events = _ledger_sweep(tmp_path, monkeypatch, "warm")
        s.assert_no_recompile(snap, "warm exec-cache sweep")
    assert s.backend_compiles == 0
    _assert_same_results(baseline, warm)

    by = _by(warm_events)
    assert {e["key"] for e in by["exec_cache_hit"]} == {"A", "B"}
    # no compile_start with real=true anywhere in the warm run
    assert not [e for e in warm_events
                if e["event"] == "compile_start" and e.get("real")]
    for ev in by["compile_end"]:
        assert ev["cache"] == "exec_cache" and ev["xla_compiles"] == 0
    assert len(by["compile_overlap"]) == 1


def test_precompile_warms_sweep(tmp_path, monkeypatch, exec_cache, baseline):
    """sweep.precompile() builds + memoizes the executables without
    dispatching anything; the following sweep() reuses them via the
    template memo (compile_cache hit, zero compiles)."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE", exec_cache)
    sweep_mod._TEMPLATE_MEMO.clear()
    report = sweep_mod.precompile(demo_spar(nw_freqs=(0.05, 0.4)), AXES,
                                  STATES, n_iter=8, chunk_size=2)
    assert report["mode"] == "plain"
    assert set(report["compiled"]) == {"A", "B"}
    for info in report["compiled"].values():
        assert info["source"] in ("compile", "exec_cache")

    with RecompileSentinel() as s:
        snap = s.snapshot()
        out, events = _ledger_sweep(tmp_path, monkeypatch, "after-pre")
        s.assert_no_recompile(snap, "sweep after precompile")
    _assert_same_results(baseline, out)
    assert _by(events).get("compile_cache"), "expected a template-memo hit"

    # repeat precompile: everything already memoized in-process
    assert sweep_mod.precompile(demo_spar(nw_freqs=(0.05, 0.4)), AXES,
                                STATES, n_iter=8,
                                chunk_size=2)["cache"] == "memo"


# ---------------------------------------------------------------------------
# sweep-level: overlap accounting + knob bit-identity
# ---------------------------------------------------------------------------


def test_slow_compile_overlaps_host_work(tmp_path, monkeypatch):
    """Fault-injected slow compile: the host plan phases (resident
    upload et al.) provably run WHILE both compiles are pending, and the
    ledger's compile_overlap accounting agrees with the profiling phase
    stats at the join."""
    monkeypatch.delenv("RAFT_TPU_EXEC_CACHE", raising=False)
    sweep_mod._TEMPLATE_MEMO.clear()

    uploaded = threading.Event()
    hook_saw_upload = {}

    def listener(name, seconds):
        if name.endswith("sweep/resident_upload"):
            uploaded.set()

    def slow_compile_hook(key):
        # blocks the worker until the MAIN thread has finished the
        # resident upload — if host work did not overlap the compiles,
        # this would deadlock the sweep until the 60 s timeout
        hook_saw_upload[key] = uploaded.wait(timeout=60.0)

    profiling.add_listener(listener)
    monkeypatch.setattr(cs, "_COMPILE_HOOK", slow_compile_hook)
    profiling.reset()
    try:
        out, events = _ledger_sweep(tmp_path, monkeypatch, "overlap")
    finally:
        profiling.remove_listener(listener)
    assert hook_saw_upload == {"A": True, "B": True}
    assert np.isfinite(out["motion_std"]).all()

    by = _by(events)
    ov, = by["compile_overlap"]
    stats = profiling.stats()
    # the join stall is the same interval the profiling phase timed
    stall_phase = stats["sweep/chunks/wait_executable"]["total"]
    assert abs(ov["stall_s"] - stall_phase) < 0.25, (ov, stall_phase)
    # per-executable compile time landed in worker-thread phases
    for key in ("A", "B"):
        assert stats[f"compile/{key}"]["calls"] == 1
    longest = max(stats[f"compile/{k}"]["total"] for k in ("A", "B"))
    assert ov["compile_s"] >= longest - 0.25
    # overlap identity: stall + hidden ~ compile critical path when host
    # work is shorter than the compile (it is here — the hook blocks the
    # workers until the host side finished)
    assert ov["host_s"] > 0.0
    assert ov["hidden_s"] <= ov["host_s"] + 1e-6
    assert ov["stall_s"] <= ov["compile_s"] + 0.25
    profiling.reset()


def test_service_off_and_pipeline_depths_bit_identical(
        monkeypatch, exec_cache, baseline):
    """RAFT_TPU_COMPILE_SERVICE=0 (inline builds, no background
    threads) and pipeline depth 1 vs 3 all reproduce the baseline
    bit-for-bit, service on and off."""
    monkeypatch.setenv("RAFT_TPU_EXEC_CACHE", exec_cache)

    monkeypatch.setenv("RAFT_TPU_COMPILE_SERVICE", "0")
    sweep_mod._TEMPLATE_MEMO.clear()
    inline = _sweep()
    _assert_same_results(baseline, inline)

    for depth in ("1", "3"):
        monkeypatch.setenv("RAFT_TPU_PIPELINE", depth)
        monkeypatch.setenv("RAFT_TPU_COMPILE_SERVICE", "0")
        sweep_mod._TEMPLATE_MEMO.clear()
        off = _sweep()
        monkeypatch.setenv("RAFT_TPU_COMPILE_SERVICE", "1")
        sweep_mod._TEMPLATE_MEMO.clear()
        on = _sweep()
        _assert_same_results(off, on)
        _assert_same_results(baseline, on)
