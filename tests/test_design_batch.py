"""Batched design compiler tests: the sweep axis as an array axis.

Checks that the probe-parsed, stacked, vmapped design-compile path
produces the same answers as the per-variant model path (the reference
pattern, raft/parametersweep.py:56-100), and that out-of-scope axes are
detected and rejected cleanly.
"""

import numpy as np
import pytest

from raft_tpu.designs import demo_spar


def _demo():
    return demo_spar(nw_freqs=(0.05, 0.4))


AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]


def test_batched_matches_per_variant_path():
    from raft_tpu import sweep as sweep_mod
    from raft_tpu.parallel.design_batch import SweepAxisError

    out_new = sweep_mod.sweep(_demo(), AXES, STATES, n_iter=6)

    orig = sweep_mod.stack_variants

    def force_fallback(*a, **k):
        raise SweepAxisError("forced")

    sweep_mod.stack_variants = force_fallback
    try:
        out_old = sweep_mod.sweep(_demo(), AXES, STATES, n_iter=6)
    finally:
        sweep_mod.stack_variants = orig

    a, b = out_new["motion_std"], out_old["motion_std"]
    assert np.max(np.abs(a - b)) <= 1e-10 * np.max(np.abs(b))


def test_batch_compiler_params_match_design_params():
    """compile_one on parsed leaves == calcStatics+calcHydroConstants+
    design_params on the full model, leaf for leaf (node order may
    differ between the grouped and the member-ordered layout; all node
    quantities enter only through sums)."""
    import copy

    import jax
    import jax.numpy as jnp

    from raft_tpu.core.model import Model
    from raft_tpu.parallel.case_solve import design_params
    from raft_tpu.parallel.design_batch import make_batch_compiler, stack_variants

    design = _demo()
    model = Model(copy.deepcopy(design))
    fowt = model.fowtList[0]
    fowt.setPosition(np.zeros(6))
    fowt.calcStatics()
    fowt.calcHydroConstants()
    p_ref, s_ref = design_params(fowt, include_aero=False)

    compile_one, static = make_batch_compiler(fowt)
    assert static == s_ref
    stacked, treedef, _ = stack_variants(design, [], [()], rho=fowt.rho_water, g=fowt.g)
    leaves = [jnp.asarray(lf[0]) for lf in stacked]
    geoms, moor = jax.tree_util.tree_unflatten(treedef, leaves)
    p_new = compile_one(geoms, moor)

    np.testing.assert_allclose(np.asarray(p_new["C"]), np.asarray(p_ref["C"]), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(p_new["M"][0]), np.asarray(p_ref["M"][0]), rtol=1e-12)
    for key in p_ref["nodes"]:
        a = np.sort(np.asarray(p_ref["nodes"][key]).ravel())
        b = np.sort(np.asarray(p_new["nodes"][key]).ravel())
        np.testing.assert_allclose(b, a, rtol=1e-12, atol=1e-14, err_msg=key)


def test_cross_axis_interaction_detected():
    """Two axes writing into the same member force the exact
    per-combination parse, and the result still matches the per-variant
    model path."""
    from raft_tpu import sweep as sweep_mod
    from raft_tpu.parallel.design_batch import SweepAxisError

    # both axes feed member 0's geometry; 'stations' rescales l_fill_frac,
    # so the d-leaf and the l_fill_frac-leaf interact through parsing
    axes = [
        ("platform.members.0.d", [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5]]),
        ("platform.members.0.t", [0.05, 0.06]),
    ]
    out_new = sweep_mod.sweep(_demo(), axes, STATES[:1], n_iter=6)

    orig = sweep_mod.stack_variants
    sweep_mod.stack_variants = lambda *a, **k: (_ for _ in ()).throw(SweepAxisError("x"))
    try:
        out_old = sweep_mod.sweep(_demo(), axes, STATES[:1], n_iter=6)
    finally:
        sweep_mod.stack_variants = orig
    a, b = out_new["motion_std"], out_old["motion_std"]
    assert np.max(np.abs(a - b)) <= 1e-10 * np.max(np.abs(b))


def test_out_of_scope_axis_rejected():
    from raft_tpu.parallel.design_batch import SweepAxisError, stack_variants

    design = _demo()
    with pytest.raises(SweepAxisError):
        stack_variants(design, [("site.rho_water", [1000.0, 1025.0])],
                       [(1000.0,), (1025.0,)], rho=1025.0, g=9.81)


def test_callable_axis():
    """Callable axes (arbitrary design-dict mutations) go through the
    same probe machinery."""
    from raft_tpu import sweep as sweep_mod

    def set_d(design, val):
        design["platform"]["members"][0]["d"] = val

    out = sweep_mod.sweep(
        _demo(),
        [(set_d, [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5]])],
        STATES[:1], n_iter=6,
    )
    assert out["motion_std"].shape == (2, 1, 6)
    assert np.all(np.isfinite(out["motion_std"]))
    assert not np.allclose(out["motion_std"][0], out["motion_std"][1])


def test_sweep_props_and_contours(tmp_path):
    """Per-design properties (getOutputs parity: mass/displacement/GMT)
    and the reference-style contour postprocessing
    (raft/parametersweep.py:9-54, 119-561)."""
    import os

    from raft_tpu import sweep as sweep_mod
    from raft_tpu.sweep_post import grid_metric, plot_sweep_contours

    axes = [
        ("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.2, 10.2, 6.5, 6.5]]),
        ("platform.members.0.rho_fill", [[1700.0, 0, 0], [1900.0, 0, 0]]),
    ]
    out = sweep_mod.sweep(_demo(), axes, STATES[:1], n_iter=4)

    for key in ("mass", "displacement", "GMT"):
        assert np.all(np.isfinite(out[key])), key
    G_mass = grid_metric(out, axes, "mass")
    G_disp = grid_metric(out, axes, "displacement")
    assert G_mass.shape == (2, 2)
    # a fatter main column adds steel mass and displaced volume
    assert np.all(G_mass[1] > G_mass[0])
    assert np.all(G_disp[1] > G_disp[0])
    # denser ballast adds mass but no displacement
    assert np.all(G_mass[:, 1] > G_mass[:, 0])
    np.testing.assert_allclose(G_disp[:, 1], G_disp[:, 0], rtol=1e-9)

    paths = plot_sweep_contours(out, axes, metrics=["mass", "GMT", "surge_std"],
                                out_dir=str(tmp_path))
    assert len(paths) == 3
    for p in paths:
        assert os.path.getsize(p) > 10_000  # a real rendered figure


def test_sweep_nacelle_acceleration_channel():
    """AxRNA_std: nacelle fore-aft acceleration std per (design, case) —
    the saveTurbineOutputs channel WEIS's Max_Nacelle_Acc reads
    (raft_fowt.py:1930-1945), reduced on device in the batched sweep."""
    from raft_tpu import sweep as sweep_mod

    out = sweep_mod.sweep(_demo(), AXES, STATES, n_iter=4)
    a = out["AxRNA_std"]
    assert a.shape == (2, 2)
    assert np.all(np.isfinite(a)) and np.all(a > 0)
    # rougher sea state -> larger nacelle acceleration for every design
    assert np.all(a[:, 1] > a[:, 0])


def test_turbine_axis_batched():
    """A turbine-dict axis (RNA mass) rides the batched path as a
    per-variant RNA/aero gather (the OMDAO DOE surface varies turbine
    parameters, omdao_raft.py:480-696): the factorial sweep must equal
    independent sweeps with each turbine value baked into the base
    design, and the results must actually vary along the turbine axis."""
    import copy

    from raft_tpu import sweep as sweep_mod

    base = _demo()
    m0 = base["turbine"]["mRNA"]
    turb_vals = [0.7 * m0, 1.3 * m0]
    geom_axis = ("platform.members.0.d",
                 [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5]])
    out = sweep_mod.sweep(base, [("turbine.mRNA", turb_vals), geom_axis],
                          STATES, n_iter=6)
    assert out["motion_std"].shape == (4, 2, 6)
    assert np.all(np.isfinite(out["motion_std"]))
    # heavier RNA shifts the response: the turbine axis is live
    assert not np.allclose(out["motion_std"][:2], out["motion_std"][2:])

    for iv, v in enumerate(turb_vals):
        d = copy.deepcopy(base)
        d["turbine"]["mRNA"] = v
        ref = sweep_mod.sweep(d, [geom_axis], STATES, n_iter=6)
        rows = slice(2 * iv, 2 * iv + 2)
        np.testing.assert_allclose(out["motion_std"][rows],
                                   ref["motion_std"], rtol=1e-9, atol=1e-14)
        np.testing.assert_allclose(out["AxRNA_std"][rows],
                                   ref["AxRNA_std"], rtol=1e-9, atol=1e-14)
        np.testing.assert_allclose(out["mass"][rows], ref["mass"], rtol=1e-9)


def test_turbine_axis_batched_with_wind():
    """Turbine axis + wind: per-variant aero-servo impedance (A/B) and
    hub height must be substituted per design, matching sweeps with the
    turbine value baked in (reference behavior: calcTurbineConstants
    re-runs per design point, raft_model.py:545)."""
    import copy

    import yaml

    from raft_tpu import sweep as sweep_mod

    with open("/root/reference/tests/test_data/VolturnUS-S.yaml") as f:
        base = yaml.load(f, Loader=yaml.FullLoader)
    base.setdefault("settings", {})
    base["settings"]["min_freq"] = 0.05
    base["settings"]["max_freq"] = 0.4

    h0 = float(base["turbine"]["hHub"])
    turb_vals = [h0, h0 + 15.0]
    geom_axis = ("platform.members.0.d", [10.0, 10.8])
    wind = [{"wind_speed": 8.0}, {"wind_speed": 12.0}]
    states = [(4.0, 8.0), (6.0, 10.0)]

    out = sweep_mod.sweep(base, [("turbine.hHub", turb_vals), geom_axis],
                          states, n_iter=6, wind=wind)
    assert np.all(np.isfinite(out["motion_std"]))
    # a taller tower-top changes the aero impedance arm + nacelle channel
    assert not np.allclose(out["AxRNA_std"][:2], out["AxRNA_std"][2:])

    for iv, v in enumerate(turb_vals):
        d = copy.deepcopy(base)
        d["turbine"]["hHub"] = v
        ref = sweep_mod.sweep(d, [geom_axis], states, n_iter=6, wind=wind)
        rows = slice(2 * iv, 2 * iv + 2)
        np.testing.assert_allclose(out["motion_std"][rows],
                                   ref["motion_std"], rtol=1e-9, atol=1e-14)
        np.testing.assert_allclose(out["AxRNA_std"][rows],
                                   ref["AxRNA_std"], rtol=1e-9, atol=1e-14)


def test_sweep_template_memoization():
    """Repeat sweeps of the same base design reuse the compiled template
    (model + batched compiler + chunk executable): the second call must
    not rebuild the design compiler, and new axis values still give
    correct, distinct results through the cached executable."""
    from raft_tpu import sweep as sweep_mod
    from raft_tpu.parallel import design_batch

    design = _demo()
    calls = []
    orig = design_batch.make_batch_compiler

    def spy(fowt):
        calls.append(1)
        return orig(fowt)

    design_batch.make_batch_compiler = spy
    try:
        sweep_mod._TEMPLATE_MEMO.clear()
        out1 = sweep_mod.sweep(design, AXES, STATES, n_iter=4)
        axes2 = [(AXES[0][0], [[9.6, 9.6, 6.5, 6.5], [10.4, 10.4, 6.5, 6.5]])]
        out2 = sweep_mod.sweep(design, axes2, STATES, n_iter=4)
        assert len(calls) == 1  # compiler built once, reused on the repeat
        assert np.all(np.isfinite(out2["motion_std"]))
        assert not np.allclose(out1["motion_std"], out2["motion_std"])
        # a different design content misses the memo and compiles fresh
        d3 = _demo()
        d3["platform"]["members"][0]["t"] = 0.06
        sweep_mod.sweep(d3, AXES, STATES[:1], n_iter=4)
        assert len(calls) == 2
        assert len(sweep_mod._TEMPLATE_MEMO) == 2
    finally:
        design_batch.make_batch_compiler = orig


def test_turbine_variant_fowt_matches_full_model_build():
    """_turbine_variant_fowt is the sweep's fast path for aero axes: a
    shallow FOWT copy with just the rotors rebuilt from the mutated
    turbine dict.  Its solver-facing outputs (rna_params_for pytree, hub
    heights, and — with wind — the A/B aero-servo tables) must equal a
    full Model build of the same mutated design, or turbine sweeps
    silently diverge from the reference per-point rebuild."""
    import copy

    import jax

    from raft_tpu import sweep as sweep_mod
    from raft_tpu.core.model import Model
    from raft_tpu.parallel.design_batch import rna_params_for

    base = _demo()
    m0 = base["turbine"]["mRNA"]
    hub0 = base["turbine"]["hHub"]
    axes = [("turbine.mRNA", [m0, 1.4 * m0]),
            ("turbine.hHub", [hub0, hub0 + 12.0])]
    combo = (1.4 * m0, hub0 + 12.0)

    template = Model(copy.deepcopy(base))
    fowt = template.fowtList[0]
    fowt.setPosition(np.zeros(6))

    fv = sweep_mod._turbine_variant_fowt(fowt, base, axes, [0, 1], combo)

    d_full = copy.deepcopy(base)
    d_full["turbine"]["mRNA"] = combo[0]
    d_full["turbine"]["hHub"] = combo[1]
    full = Model(d_full).fowtList[0]
    full.setPosition(np.zeros(6))

    rna_v = jax.tree_util.tree_map(np.asarray, rna_params_for(fv))
    rna_f = jax.tree_util.tree_map(np.asarray, rna_params_for(full))
    assert set(rna_v) == set(rna_f)
    for key in rna_f:
        np.testing.assert_allclose(rna_v[key], rna_f[key], rtol=1e-12,
                                   atol=0, err_msg=key)
    # the variant actually moved off the template (axis is live)
    assert not np.allclose(rna_v["mRNA"],
                           np.asarray(rna_params_for(fowt)["mRNA"]))

    zh_v = np.asarray([float(r.r3[2]) for r in fv.rotorList])
    zh_f = np.asarray([float(r.r3[2]) for r in full.rotorList])
    np.testing.assert_allclose(zh_v, zh_f, rtol=1e-12)
    assert zh_v[0] != pytest.approx(float(fowt.rotorList[0].r3[2]))

    # the template FOWT must be untouched by the variant build
    assert float(fowt.rotorList[0].mRNA) == pytest.approx(m0)
    assert np.asarray(rna_params_for(fowt)["mRNA"])[0] == pytest.approx(m0)


@pytest.mark.slow
def test_fifty_value_turbine_axis_plan_time_bounded():
    """A control-gain-style DOE — one turbine axis, 50 values — must
    plan in O(light-variant) host time: the sweep builds ONE full Model
    template and then light per-combo FOWT rebuilds (shallow copy +
    rotor rebuild, sweep._turbine_variant_fowt), never 50 Model builds.
    Timed directly against the full build so the bound tracks the
    machine, then exercised end-to-end through the sweep."""
    import copy
    import time

    from raft_tpu import sweep as sweep_mod
    from raft_tpu.core.model import Model
    from raft_tpu.robust import STATUS_OK

    base = _demo()
    m0 = base["turbine"]["mRNA"]
    values = [float(v) for v in np.linspace(0.7 * m0, 1.3 * m0, 50)]
    axes = [("turbine.mRNA", values)]

    t0 = time.perf_counter()
    template = Model(copy.deepcopy(base))
    t_full = time.perf_counter() - t0
    fowt = template.fowtList[0]
    fowt.setPosition(np.zeros(6))

    t0 = time.perf_counter()
    variants = [sweep_mod._turbine_variant_fowt(fowt, base, axes, [0], (v,))
                for v in values]
    t_light = time.perf_counter() - t0
    assert len(variants) == 50
    # 50 light rebuilds must cost no more than ~10 extra full builds
    # (a regression to per-variant Model() costs 50x and trips this)
    assert t_light < max(5.0, 10.0 * t_full), (t_light, t_full)
    # each variant is live (the axis value landed in the rotors)
    mrna = np.asarray([float(v.rotorList[0].mRNA) for v in variants])
    np.testing.assert_allclose(mrna, values, rtol=1e-12)

    out = sweep_mod.sweep(base, axes, STATES[:1], n_iter=8)
    assert out["motion_std"].shape == (50, 1, 6)
    assert np.isfinite(out["motion_std"]).all()
    assert (out["status"] == STATUS_OK).all()
