"""Every design YAML shipped with the reference loads and runs.

The reference's designs/ directory is the de-facto schema corpus:
OC3spar (spar), OC4semi (semisub), VolturnUS-S (+farm), FOCTT
(tension-leg concept with mixed 4/5-column airfoil polars),
RM1_Floating (MHK, twin underwater rotors), Vertical_cylinder
(minimal). Constructing a Model exercises the full schema parser,
member compiler, rotor polar pipeline, and mooring assembly.

Note: VolturnUS-S_farm.yaml references SharedMooring2.dat, which the
reference repository does not ship — the design is unrunnable verbatim
upstream too — so the farm case substitutes the test-data MoorDyn file.
"""

import glob
import os

import numpy as np
import pytest

import raft_tpu
from raft_tpu.schema import load_design

DESIGNS = sorted(glob.glob("/root/reference/designs/*.yaml"))
TEST_DATA = "/root/reference/tests/test_data"

pytestmark = pytest.mark.skipif(not DESIGNS, reason="reference designs absent")


@pytest.mark.parametrize("path", DESIGNS, ids=[os.path.basename(p) for p in DESIGNS])
def test_design_constructs(path):
    design = load_design(path)
    if "array_mooring" in design:
        design["array_mooring"]["file"] = os.path.join(
            TEST_DATA, "shared_mooring_volturnus.dat")
    model = raft_tpu.Model(design)
    assert len(model.fowtList) >= 1
    for fowt in model.fowtList:
        fowt.setPosition(np.zeros(6) if len(model.fowtList) == 1 else fowt.r6)
        fowt.calcStatics()
        assert np.isfinite(fowt.M_struc).all()
        assert fowt.M_struc[0, 0] > 0


@pytest.mark.parametrize("name", ["FOCTT_example.yaml", "Vertical_cylinder.yaml"])
def test_design_unloaded_equilibrium(name):
    """End-to-end unloaded statics on designs not covered elsewhere.

    FOCTT is a weight-heavy CT-Opt tidal device (its unloaded state
    genuinely sinks until column buoyancy + chain lift balance, and its
    surge stiffness is near zero with slack lines), so the assertion is
    on a converged, in-water equilibrium — not on small offsets."""
    path = os.path.join("/root/reference/designs", name)
    model = raft_tpu.Model(path)
    model.analyzeUnloaded()
    off = np.asarray(model.results["properties"]["offset_unloaded"])
    assert np.all(np.isfinite(off))
    depth = model.depth
    assert -depth < off[2] < 10.0          # still in the water column
    assert np.all(np.abs(off[3:]) < 0.5)   # small rotations (rad)
    if name == "Vertical_cylinder.yaml":
        assert np.all(np.abs(off[:2]) < 5.0)


EXAMPLES = "/root/reference/examples"


@pytest.mark.skipif(not os.path.isdir(EXAMPLES), reason="reference examples absent")
def test_wamit_coefs_example_end_to_end():
    """The OC4semi-WAMIT_Coefs example (potModMaster 3 + hydroPath with a
    repo-root-relative path): read .1/.12d, run a case, finite response.
    The reference ships no .3 file, so excitation falls back to strip
    theory with a warning — same graceful path as read_hydro documents."""
    model = raft_tpu.Model(os.path.join(EXAMPLES, "OC4semi-WAMIT_Coefs.yaml"))
    fowt = model.fowtList[0]
    assert np.any(fowt.A_BEM != 0)          # .1 file was read
    assert getattr(fowt, "qtf", None) is not None  # .12d file was read
    model.analyzeUnloaded()
    model.analyzeCases()
    cm = model.results["case_metrics"][0][0]
    assert np.isfinite(cm["surge_std"]) and cm["surge_std"] > 0
