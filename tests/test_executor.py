"""Device-resident pipelined chunk executor (raft_tpu.parallel.executor).

The executor's contract is that its knobs change SCHEDULING, never
results: the resident on-device gather vs legacy host packing, pipeline
depth 1 vs 3, and fault isolation through the resident path must all
produce bit-identical sweep outputs from the same compiled executables,
with zero extra XLA compiles once the executables are warm.  The
coalescing checkpoint writer must preserve the synchronous path's
durability contract (final state on disk when sweep() returns) without
the hot loop ever blocking on np.savez.
"""

import threading

import numpy as np
import pytest

from raft_tpu import profiling
from raft_tpu import sweep as sweep_mod
from raft_tpu.config import executor_config
from raft_tpu.designs import demo_spar
from raft_tpu.parallel.executor import CheckpointWriter
from raft_tpu.robust import STATUS_OK, STATUS_QUARANTINED

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]


def _sweep(**kw):
    kw.setdefault("n_iter", 8)
    kw.setdefault("chunk_size", 2)
    return sweep_mod.sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES, **kw)


def _assert_same_results(a, b):
    np.testing.assert_array_equal(a["motion_std"], b["motion_std"])
    np.testing.assert_array_equal(a["AxRNA_std"], b["AxRNA_std"])
    np.testing.assert_array_equal(a["status"], b["status"])
    for k in ("mass", "displacement", "GMT"):
        np.testing.assert_array_equal(a[k], b[k])


# ---------------------------------------------------------------------------
# config knobs
# ---------------------------------------------------------------------------


def test_executor_config_defaults_and_env(monkeypatch):
    cfg = executor_config()
    assert cfg == {"resident": True, "pipeline_depth": 2}
    monkeypatch.setenv("RAFT_TPU_RESIDENT", "0")
    monkeypatch.setenv("RAFT_TPU_PIPELINE", "5")
    cfg = executor_config()
    assert cfg["resident"] is False and cfg["pipeline_depth"] == 5
    # depth floors at 1 (0 would deadlock the commit loop)
    monkeypatch.setenv("RAFT_TPU_PIPELINE", "0")
    assert executor_config()["pipeline_depth"] == 1
    with pytest.raises(ValueError, match="unknown executor config"):
        executor_config({"residnt": True})


# ---------------------------------------------------------------------------
# scheduling knobs never change results (bit-identity)
# ---------------------------------------------------------------------------


@pytest.mark.sentinel
def test_executor_variants_bit_identical_no_recompile(monkeypatch):
    """Resident vs legacy packing, pipeline depth 1 vs 3, and a
    fault-injected chunk must (a) reuse the warm executables with ZERO
    new XLA compiles and (b) reproduce the baseline bit-for-bit (the
    quarantined row excepted — it is NaN by contract)."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    base = _sweep()  # warm: compiles + memoizes executables AND gather
    assert (base["status"] == STATUS_OK).all()

    with RecompileSentinel() as s:
        snap = s.snapshot()

        monkeypatch.setenv("RAFT_TPU_RESIDENT", "0")
        legacy = _sweep()
        s.assert_no_recompile(snap, "legacy-packing sweep")
        _assert_same_results(base, legacy)

        monkeypatch.delenv("RAFT_TPU_RESIDENT")
        monkeypatch.setenv("RAFT_TPU_PIPELINE", "1")
        depth1 = _sweep()
        s.assert_no_recompile(snap, "depth-1 sweep")
        _assert_same_results(base, depth1)

        monkeypatch.setenv("RAFT_TPU_PIPELINE", "3")
        depth3 = _sweep()
        s.assert_no_recompile(snap, "depth-3 sweep")
        _assert_same_results(base, depth3)

        # fault injection through the resident gather: the bisection
        # re-runs ride the same padded chunk executables
        poison = 1

        def hook(idx, dispatch):
            if (np.asarray(idx) == poison).any():
                raise RuntimeError("injected chunk fault")
            return dispatch(idx)

        monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
        with pytest.warns(RuntimeWarning, match="isolating faults"):
            faulted = _sweep()
        s.assert_no_recompile(snap, "fault-isolating sweep")

    assert faulted["status"][poison] == STATUS_QUARANTINED
    ok = faulted["status"] == STATUS_OK
    assert ok.tolist() == [i != poison for i in range(4)]
    np.testing.assert_array_equal(faulted["motion_std"][ok],
                                  base["motion_std"][ok])
    assert np.isnan(faulted["motion_std"][poison]).all()


def test_chunk_phase_split_recorded():
    """The executor's per-stage phases land under sweep/chunks (what
    bench.py reports as chunk_split_s)."""
    _sweep()  # warm so the phase times reflect the steady state
    profiling.reset()
    _sweep()
    rep = profiling.report()
    for stage in ("gather", "compute", "fetch", "commit"):
        assert f"sweep/chunks/{stage}" in rep, (stage, sorted(rep))
    assert "sweep/chunks/isolate" not in rep  # healthy sweep
    profiling.reset()


def test_resident_checkpoint_final_state_complete(tmp_path, monkeypatch):
    """With the background writer and a deep pipeline, the on-disk
    checkpoint at sweep() return still holds the COMPLETE final state
    (close() flushes the last snapshot before the sweep returns)."""
    monkeypatch.setenv("RAFT_TPU_PIPELINE", "3")
    ckpt = str(tmp_path / "sweep.npz")
    out = _sweep(checkpoint=ckpt)
    with np.load(ckpt) as dat:
        assert dat["done"].all()
        np.testing.assert_array_equal(dat["motion_std"], out["motion_std"])
        np.testing.assert_array_equal(dat["status"], out["status"])


# ---------------------------------------------------------------------------
# CheckpointWriter unit behavior
# ---------------------------------------------------------------------------


def test_checkpoint_writer_coalesces_latest_wins():
    """Rapid submissions while a write is in flight coalesce: only the
    in-flight state and the LAST submitted state reach the disk."""
    written = []
    first_in = threading.Event()
    release = threading.Event()

    def write(state):
        if state == 0:
            first_in.set()
            assert release.wait(timeout=5.0)
        written.append(state)

    w = CheckpointWriter(write)
    w.submit(0)
    assert first_in.wait(timeout=5.0)
    for i in range(1, 50):  # all queued while write(0) is blocked
        w.submit(i)
    release.set()
    w.close()
    assert written == [0, 49]
    assert w.writes == 2


def test_checkpoint_writer_flushes_pending_on_close():
    written = []
    w = CheckpointWriter(written.append)
    w.submit("final")
    w.close()
    assert written[-1] == "final"
    with pytest.raises(RuntimeError, match="closed"):
        w.submit("late")


def test_checkpoint_writer_error_warns_not_raises():
    """A failing write (disk full) must not kill the sweep it protects:
    surfaced as ONE RuntimeWarning at close, never an exception."""
    def write(state):
        raise OSError("disk full")

    w = CheckpointWriter(write)
    w.submit(1)
    w.submit(2)
    with pytest.warns(RuntimeWarning, match="checkpoint write failed"):
        w.close()
