"""Farm wake layer (FLORIS-coupling equivalent): Gaussian wake model,
power/thrust curve generation, wake-coupled equilibrium, and AEP."""

import numpy as np
import pytest
import yaml

TEST_DATA = "/root/reference/tests/test_data"


def test_gaussian_wake_deficit():
    from raft_tpu.farm import GaussianWakeFarm

    D = 240.0
    U_tab = np.array([3.0, 10.0, 25.0])
    CT_tab = np.array([0.8, 0.8, 0.8])
    farm = GaussianWakeFarm(D, U_tab, CT_tab)
    # two turbines, one directly downstream
    xy = np.array([[0.0, 0.0], [7 * D, 0.0]])
    U_eff = np.asarray(farm.effective_speeds(xy, 10.0, wind_dir_deg=0.0))
    assert U_eff[0] == pytest.approx(10.0, rel=1e-6)  # upstream undisturbed
    assert 5.0 < U_eff[1] < 9.7                      # downstream in the wake
    # laterally offset turbine sees a weaker deficit
    xy2 = np.array([[0.0, 0.0], [7 * D, 2 * D]])
    U_off = np.asarray(farm.effective_speeds(xy2, 10.0, wind_dir_deg=0.0))
    assert U_off[1] > U_eff[1]
    # rotating the wind by 90 deg decouples the pair
    U_rot = np.asarray(farm.effective_speeds(xy, 10.0, wind_dir_deg=90.0))
    assert U_rot[1] == pytest.approx(10.0, rel=1e-3)


@pytest.fixture(scope="module")
def volturnus_model():
    import raft_tpu

    with open(f"{TEST_DATA}/VolturnUS-S.yaml") as f:
        design = yaml.load(f, Loader=yaml.FullLoader)
    return raft_tpu.Model(design)


def test_power_thrust_curve(volturnus_model):
    from raft_tpu.farm import power_thrust_curve

    out = power_thrust_curve(volturnus_model, [8.0, 30.0])
    # operating point produces power; parked point produces none
    assert out["P"][0] > 1e6
    assert out["P"][1] == 0.0
    assert 0.0 < out["CT"][0] < 1.2
    assert np.isfinite(out["pitch_deg"]).all()


def test_calc_aep_with_wake():
    """AEP of a 2-turbine row: waked layout yields less energy than two
    unwaked turbines, more than one."""
    from types import SimpleNamespace

    from raft_tpu.farm import GaussianWakeFarm, calc_aep

    D = 240.0
    wake = GaussianWakeFarm(D, np.array([3.0, 25.0]), np.array([0.8, 0.8]))
    model = SimpleNamespace(fowtList=[
        SimpleNamespace(x_ref=0.0, y_ref=0.0),
        SimpleNamespace(x_ref=7 * D, y_ref=0.0),
    ])
    power_curve = {"U": np.array([3.0, 8.0, 11.0, 25.0]),
                   "P": np.array([0.0, 5.0e6, 15.0e6, 15.0e6])}
    wind_rose = [(8.0, 0.0, 0.5), (8.0, 90.0, 0.5)]  # (U, dir, probability)
    aep = calc_aep(model, wake, wind_rose, power_curve)
    p1 = np.interp(8.0, power_curve["U"], power_curve["P"])
    assert aep < 2 * p1 * 8760.0          # wake losses
    assert aep > 1.2 * p1 * 8760.0        # but both turbines contribute
