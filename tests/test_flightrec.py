"""Solver flight recorder: convergence telemetry, anomaly
capture-and-replay, and Chrome-trace timeline export.

Contracts pinned here:

* the per-iteration Borgman residual trace is an opt-in ``lax.scan``
  ys channel — correct shape/dtype, finite on healthy designs, and the
  metrics it feeds (``convergence_summary`` events, iterations-to-
  tolerance) are consistent with the recorded trajectories;
* recorder OFF is the seed trace: bit-identical results and ZERO
  additional XLA compiles (sentinel-pinned);
* a fault-injected sweep with a capture directory armed writes a
  self-contained replay bundle whose standalone replay reproduces the
  recorded health/status arrays (ISSUE acceptance);
* ``obs.timeline`` emits valid Chrome trace-event JSON with per-device
  tracks on the 8-virtual-device CPU mesh (ISSUE acceptance).

Tests whose sweep shapes compile executables beyond the warm tier-1
pipeline (capture/replay at chunk extent 1, the 4-device timeline
topology, the health-off and capability-fallback variants) are marked
``slow``: tier-1 keeps the config/metrics/sentinel contracts, and the
CI lint job runs this file in full (see the flight-recorder step in
``.github/workflows/ci.yml``).
"""

import json
import os

import numpy as np
import pytest

import jax

from raft_tpu import sweep as sweep_mod
from raft_tpu.config import flightrec_config, health_config
from raft_tpu.designs import demo_spar
from raft_tpu.obs import flightrec as obs_flightrec
from raft_tpu.obs import ledger as obs_ledger
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import report as obs_report
from raft_tpu.obs import schema as obs_schema
from raft_tpu.obs import timeline as obs_timeline
from raft_tpu.robust import (STATUS_NAN, STATUS_OK, STATUS_QUARANTINED,
                             iterations_to_tolerance)

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]
N_ITER = 8


def _sweep(**kw):
    kw.setdefault("n_iter", N_ITER)
    kw.setdefault("chunk_size", 2)
    return sweep_mod.sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES,
                           **kw)


def _ledger_sweep(tmp_path, monkeypatch, name, **kw):
    ldir = tmp_path / name
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    out = _sweep(**kw)
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    runs = obs_ledger.list_runs(str(ldir))
    assert len(runs) == 1, runs
    return out, obs_ledger.read_events(runs[0]), runs[0]


def _by(events):
    out = {}
    for ev in events:
        out.setdefault(ev["event"], []).append(ev)
    return out


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


def test_flightrec_config_env_and_overrides(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_FLIGHTREC", raising=False)
    cfg = flightrec_config()
    assert cfg["enabled"] is False and cfg["dir"] is None
    assert cfg["convergence"] is True

    monkeypatch.setenv("RAFT_TPU_FLIGHTREC", "/tmp/caps")
    monkeypatch.setenv("RAFT_TPU_FLIGHTREC_SEVERITY", "non-converged")
    monkeypatch.setenv("RAFT_TPU_FLIGHTREC_MAX", "3")
    cfg = flightrec_config()
    assert cfg["enabled"] is True and cfg["dir"] == "/tmp/caps"
    assert cfg["severity"] == "non-converged" and cfg["max_bundles"] == 3

    assert flightrec_config({"enabled": False})["enabled"] is False
    with pytest.raises(ValueError, match="unknown flightrec"):
        flightrec_config({"nope": 1})

    assert obs_flightrec.resolve_severity("nan") == STATUS_NAN
    assert obs_flightrec.resolve_severity("quarantined") == \
        STATUS_QUARANTINED
    assert obs_flightrec.resolve_severity(2) == 2
    with pytest.raises(ValueError, match="severity"):
        obs_flightrec.resolve_severity("bogus")


def test_resid_trace_requires_health():
    from raft_tpu.parallel.case_solve import make_parametric_solver

    with pytest.raises(ValueError, match="resid_trace requires"):
        make_parametric_solver({"nw": 4}, with_health=False,
                               resid_trace=True)


@pytest.mark.slow
def test_health_off_sweep_disables_trace():
    # at the sweep level: health off silently disables the trace rather
    # than failing a production run over telemetry
    out = _sweep(health=False, flightrec=True)
    assert "convergence" not in out


# ---------------------------------------------------------------------------
# convergence telemetry
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_convergence_trace_contract(tmp_path, monkeypatch):
    """Trace shape/dtype, trajectory sanity, ledger events, and the
    iterations-to-tolerance attachment."""
    out, events, path = _ledger_sweep(tmp_path, monkeypatch, "conv",
                                      flightrec=True)
    conv = out["convergence"]
    trace = conv["resid_trace"]
    assert trace.shape == (4, len(STATES), N_ITER)
    assert trace.dtype == np.float64  # x64: the solve's real dtype
    assert np.isfinite(trace).all()
    # the fixed-point iteration contracts: final residual no worse than
    # the first, and the recorded per-design health residual IS the
    # trace's last iteration (same scan, same value)
    assert (trace[..., -1] <= trace[..., 0]).all()
    np.testing.assert_array_equal(out["health"]["resid"],
                                  np.max(trace[..., -1], axis=-1))
    assert conv["iters_to_tol"].shape == (4, len(STATES))
    assert conv["iters_to_tol"].dtype == np.int32

    assert obs_schema.validate_events(events) == []
    summaries = _by(events).get("convergence_summary")
    assert summaries and len(summaries) == 2  # one per chunk
    seen = []
    for ev in summaries:
        assert ev["n_iter"] == N_ITER
        assert len(ev["iters"]) == len(ev["final_resid"]) == 2
        seen += ev["designs"]
        tol = float(health_config()["resid_tol"])
        for i, d in enumerate(ev["designs"]):
            assert ev["iters"][i] == int(
                np.max(iterations_to_tolerance(trace[d], tol)))
    assert sorted(seen) == [0, 1, 2, 3]

    # the report CLI grows a convergence section from the same events
    assert obs_report.main([path]) == 0


@pytest.mark.slow
def test_trace_on_results_match_off(tmp_path):
    """Telemetry observes the solve, never changes it: the response
    metrics with the trace on are bit-identical to the trace-off run
    (the extra scan output is dead code for the metrics path)."""
    on = _sweep(flightrec=True)
    off = _sweep()
    for k in ("motion_std", "AxRNA_std", "status"):
        np.testing.assert_array_equal(on[k], off[k], err_msg=k)
    for k in off["health"]:
        np.testing.assert_array_equal(on["health"][k], off["health"][k])


@pytest.mark.sentinel
def test_flightrec_off_bit_identical_no_recompile(monkeypatch):
    """ISSUE acceptance: with the recorder off the sweep is the seed's
    exact trace — bit-identical results, zero additional XLA compiles,
    and executable memo keys untouched (False and None spell the same
    off path)."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    monkeypatch.delenv("RAFT_TPU_FLIGHTREC", raising=False)
    base = _sweep()  # warm
    with RecompileSentinel() as s:
        snap = s.snapshot()
        off_none = _sweep(flightrec=None)
        s.assert_no_recompile(snap, "flightrec=None sweep")
        off_false = _sweep(flightrec=False)
        s.assert_no_recompile(snap, "flightrec=False sweep")
    for out in (off_none, off_false):
        for k in ("motion_std", "AxRNA_std", "status"):
            x, y = np.asarray(base[k]), np.asarray(out[k])
            assert x.dtype == y.dtype, k
            np.testing.assert_array_equal(x, y, err_msg=k)


def test_convergence_summary_feeds_metrics(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_LEDGER", raising=False)
    monkeypatch.setenv("RAFT_TPU_METRICS", "1")
    obs_metrics.reset()
    try:
        obs_metrics.observe_event("convergence_summary", {
            "chunk": 0, "n_iter": 8, "designs": [0, 1],
            "iters": [3, 9], "final_resid": [1e-8, None]})
        obs_metrics.observe_event("capability_fallback",
                                  {"reason": "sweep_axis"})
        obs_metrics.observe_event("replay_bundle",
                                  {"design": 1, "path": "/x"})
        std = obs_metrics.std()
        assert std.convergence_iterations.count() == 2
        # the None (non-finite) residual is skipped, not crashed on
        assert std.final_residual.count() == 1
        assert std.capability_fallbacks.value(reason="sweep_axis") == 1
        assert std.replay_bundles.value() == 1
    finally:
        obs_metrics.reset()


# ---------------------------------------------------------------------------
# anomaly capture and replay
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_nan_design_capture_and_replay_roundtrip(tmp_path, capsys):
    """ISSUE acceptance: a fault-injected sweep produces a replay
    bundle whose standalone replay reproduces the recorded
    health/status arrays."""
    cap = tmp_path / "bundles"
    cap.mkdir()
    axes = [("platform.members.0.d", [9.0, 10.0, float("nan"), 12.0])]
    out = sweep_mod.sweep(demo_spar(nw_freqs=(0.05, 0.4)), axes, STATES,
                          n_iter=N_ITER, chunk_size=2,
                          flightrec={"enabled": True, "dir": str(cap)})
    assert out["status"][2] == STATUS_NAN

    bundles = obs_flightrec._list_bundles(str(cap))
    assert len(bundles) == 1
    meta, arrays = obs_flightrec.load_bundle(bundles[0])
    assert meta["design_index"] == 2
    assert meta["trigger"] == "status" and meta["status_name"] == "nan"
    assert meta["n_iter"] == N_ITER and meta["chunk_size"] == 2
    # the bundle is self-contained: mutated design + recorded outputs +
    # the exact stacked input rows the executable consumed
    assert np.isnan(np.asarray(meta["design"]
                               ["platform"]["members"][0]["d"])).any()
    for k in ("std", "a_std", "resid_trace", "health_resid",
              "health_cond"):
        assert k in arrays, k
    assert any(k.startswith("input_leaf_") for k in arrays)
    assert arrays["resid_trace"].shape == (len(STATES), N_ITER)

    report = obs_flightrec.replay(bundles[0])
    assert report["ok"], report
    assert report["status"]["match"]
    assert report["arrays"]["std"] == "bit-identical"
    assert report["arrays"]["health.resid"] == "bit-identical"

    # the CLI round-trips the same path
    capsys.readouterr()  # drop the capture sweep's display output
    assert obs_flightrec.main(["replay", bundles[0], "--json"]) == 0
    cli_report = json.loads(capsys.readouterr().out)
    assert cli_report["ok"] and cli_report["design_index"] == 2
    assert obs_flightrec.main(["list", str(cap)]) == 0
    assert obs_flightrec.main(["show", bundles[0]]) == 0


@pytest.mark.slow
def test_quarantine_capture_and_replay(tmp_path, monkeypatch):
    """Bisection give-up triggers a capture (the on_quarantine hook)
    even though the design produced no rows; the bundle records the
    fault, and a standalone replay that succeeds is reported as a
    finding rather than a mismatch."""
    _sweep()  # warm
    cap = tmp_path / "bundles"
    cap.mkdir()
    ldir = tmp_path / "ledger"
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    poison = 1

    def hook(idx, dispatch):
        if (np.asarray(idx) == poison).any():
            raise RuntimeError("injected chunk fault")
        return dispatch(idx)

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    with pytest.warns(RuntimeWarning, match="isolating faults"):
        out = _sweep(flightrec={"enabled": True, "dir": str(cap)})
    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)
    monkeypatch.delenv("RAFT_TPU_LEDGER")

    assert out["status"][poison] == STATUS_QUARANTINED
    bundles = obs_flightrec._list_bundles(str(cap))
    assert len(bundles) == 1
    meta, _ = obs_flightrec.load_bundle(bundles[0])
    assert meta["trigger"] == "quarantine"
    assert "injected chunk fault" in meta["error"]

    # the ledger carries the capture event
    events = obs_ledger.read_events(obs_ledger.list_runs(str(ldir))[0])
    assert obs_schema.validate_events(events) == []
    rb = _by(events)["replay_bundle"]
    assert rb[0]["design"] == poison and rb[0]["trigger"] == "quarantine"

    report = obs_flightrec.replay(bundles[0])
    assert report["ok"]
    assert not report["status"]["match"] and "note" in report


@pytest.mark.slow
def test_capture_respects_max_bundles(tmp_path, caplog):
    cap = tmp_path / "bundles"
    cap.mkdir()
    axes = [("platform.members.0.d",
             [float("nan"), float("nan"), float("nan"), 12.0])]
    with caplog.at_level("WARNING", logger="raft_tpu.obs.flightrec"):
        out = sweep_mod.sweep(
            demo_spar(nw_freqs=(0.05, 0.4)), axes, STATES,
            n_iter=N_ITER, chunk_size=2,
            flightrec={"enabled": True, "dir": str(cap),
                       "max_bundles": 2})
    assert any("bundle cap reached" in r.message for r in caplog.records)
    assert (out["status"][:3] == STATUS_NAN).all()
    assert len(obs_flightrec._list_bundles(str(cap))) == 2


@pytest.mark.slow
def test_capture_failure_never_breaks_the_sweep(tmp_path):
    """An unwritable capture dir degrades to a warning; results are
    unchanged (the recorder is an observer, not a participant)."""
    axes = [("platform.members.0.d", [9.0, 10.0, float("nan"), 12.0])]
    missing = tmp_path / "does" / "not" / "exist"
    ro = str(missing)
    os.makedirs(missing.parent)
    (missing.parent / "exist").write_text("a file, not a dir")
    with pytest.warns(RuntimeWarning, match="capture failed"):
        out = sweep_mod.sweep(
            demo_spar(nw_freqs=(0.05, 0.4)), axes, STATES,
            n_iter=N_ITER, chunk_size=2,
            flightrec={"enabled": True, "dir": ro})
    assert out["status"][2] == STATUS_NAN
    assert np.isfinite(out["motion_std"][[0, 1, 3]]).all()


# ---------------------------------------------------------------------------
# capability fallback guard
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fallback_emits_capability_event(tmp_path, monkeypatch):
    """Degrading to the per-variant path is recorded in the ledger even
    for strip-theory designs (where nothing is dropped, so no
    warning)."""
    from raft_tpu.parallel.design_batch import SweepAxisError

    def force_fallback(*a, **k):
        raise SweepAxisError("forced")

    monkeypatch.setattr(sweep_mod, "stack_variants", force_fallback)
    # fresh axis values: the stack memo must miss so the (patched)
    # stacker actually runs and trips the fallback
    ldir = tmp_path / "fb"
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    sweep_mod.sweep(demo_spar(nw_freqs=(0.05, 0.4)),
                    [("platform.members.0.d", [9.1, 10.1])], STATES[:1],
                    n_iter=4, chunk_size=2)
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    events = obs_ledger.read_events(obs_ledger.list_runs(str(ldir))[0])
    assert obs_schema.validate_events(events) == []
    ev = _by(events)["capability_fallback"][0]
    assert ev["reason"] == "sweep_axis" and ev["detail"] == "forced"
    assert ev["dropped"] == []


@pytest.mark.slow
def test_fallback_warns_when_bem_forces_dropped(tmp_path, monkeypatch):
    """VERDICT Weak #1 guard: a potential-flow design silently routed
    to the fallback (which never runs calcBEM) now warns that
    A_BEM/B_BEM are dropped and stamps the ledger."""
    design = demo_spar(nw_freqs=(0.05, 0.4))
    design["platform"]["potModMaster"] = 2
    ldir = tmp_path / "ledger"
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    with pytest.warns(RuntimeWarning, match="DROPS BEM added mass"):
        out = sweep_mod.sweep(design, AXES[:1], STATES, n_iter=4,
                              chunk_size=2)
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    assert out["motion_std"].shape[0] == 4
    events = obs_ledger.read_events(obs_ledger.list_runs(str(ldir))[0])
    ev = _by(events)["capability_fallback"][0]
    assert "BEM added mass/damping (A_BEM/B_BEM)" in ev["dropped"]


# ---------------------------------------------------------------------------
# timeline export
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_timeline_export_schema_and_tracks(tmp_path, monkeypatch, capsys):
    """ISSUE acceptance: obs.timeline emits valid Chrome trace-event
    JSON with per-device tracks on the 8-virtual-device CPU mesh."""
    assert len(jax.devices()) == 8  # conftest forces the virtual mesh
    axes = [("platform.members.0.d", [9.0, 9.5, 10.0, 10.5,
                                      11.0, 11.5, 12.0, 12.5])]
    ldir = tmp_path / "tl"
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    sweep_mod.sweep(demo_spar(nw_freqs=(0.05, 0.4)), axes, STATES,
                    n_iter=N_ITER, chunk_size=2,
                    devices=jax.devices()[:4], flightrec=True)
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    path = obs_ledger.list_runs(str(ldir))[0]
    events = obs_ledger.read_events(path)
    trace = obs_timeline.build_trace(events)
    assert obs_timeline.validate_trace(trace) == []
    evs = trace["traceEvents"]
    assert trace["displayTimeUnit"] == "ms"

    # per-device tracks: the 8-design sweep at chunk_size 2 uses a
    # 4-wide design axis; each device that executed a chunk gets a
    # thread with that chunk's dispatch->fetch span
    chunk_spans = [e for e in evs
                   if e["ph"] == "X" and e["pid"] == obs_timeline.PID_DEVICES]
    assert {e["tid"] for e in chunk_spans} == {0, 1, 2, 3}
    for e in chunk_spans:
        assert e["dur"] >= 0 and e["args"]["n_real"] >= 1
        assert "fetch_bytes" in e["args"]

    # host phases, compile service, and metadata naming all present
    assert any(e["ph"] == "X" and e["pid"] == obs_timeline.PID_HOST
               for e in evs)
    assert any(e["pid"] == obs_timeline.PID_COMPILE for e in evs)
    names = {(e["pid"], e.get("tid")): e["args"]["name"]
             for e in evs if e["ph"] == "M" and e["name"] == "thread_name"}
    assert names[(obs_timeline.PID_DEVICES, 0)] == "device 0"

    # the whole trace is loadable JSON via the CLI, and validates
    out_path = tmp_path / "trace.json"
    assert obs_timeline.main([path, "-o", str(out_path),
                              "--validate", "--stragglers"]) == 0
    text = capsys.readouterr().out
    assert "trace valid" in text and "straggler report" in text
    loaded = json.loads(out_path.read_text())
    assert obs_timeline.validate_trace(loaded) == []

    report = obs_timeline.straggler_report(events)
    assert sorted(report["devices"]) == [0, 1, 2, 3]
    # one chunk of 2 designs per shard: perfectly balanced fetches
    assert report["imbalance"] == pytest.approx(1.0)
    assert report["chunks"] and all(c["wall_s"] >= 0
                                    for c in report["chunks"])


def test_timeline_empty_and_faulted_ledgers(tmp_path, monkeypatch):
    assert obs_timeline.build_trace([]) == {"traceEvents": [],
                                           "displayTimeUnit": "ms"}
    # a fault-injected run still exports: instants for the fault and
    # quarantine narrative land on the host events track
    _sweep()  # warm
    poison = 1

    def hook(idx, dispatch):
        if (np.asarray(idx) == poison).any():
            raise RuntimeError("injected")
        return dispatch(idx)

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    with pytest.warns(RuntimeWarning, match="isolating faults"):
        _, events, _ = _ledger_sweep(tmp_path, monkeypatch, "flt")
    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)
    trace = obs_timeline.build_trace(events)
    assert obs_timeline.validate_trace(trace) == []
    instants = {e["name"] for e in trace["traceEvents"] if e["ph"] == "i"}
    assert "fault" in instants and "quarantined" in instants

    bad = obs_timeline.validate_trace({"traceEvents": [{"ph": "Z"}]})
    assert any("bad ph" in e for e in bad)
    assert obs_timeline.validate_trace({}) == ["missing traceEvents"]
