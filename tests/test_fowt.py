"""FOWT-layer golden parity tests.

Mirrors the reference integration suite
(/root/reference/tests/test_fowt.py): statics rollup, Morison added
mass, strip-theory excitation over a 9x4x2 wave grid, drag
linearization, and current loads for the VolturnUS-S and OC3spar
designs, validated against the reference's inline literals and pickles
at the same tolerances (rtol=1e-5).
"""

import os
import pickle

import numpy as np
import pytest
import yaml
from numpy.testing import assert_allclose

from raft_tpu.core.fowt import FOWT

from ref_goldens import load_literals

LIST_FILES = ["VolturnUS-S.yaml", "OC3spar.yaml"]

GOLDEN_NAMES = [
    "desired_rCG", "desired_rCG_sub", "desired_m_ballast", "desired_M_struc",
    "desired_M_struc_sub", "desired_C_struc", "desired_W_struc", "desired_rCB",
    "desired_C_hydro", "desired_W_hydro", "desired_A_hydro_morison",
    "desired_current_drag",
]


@pytest.fixture(scope="module")
def goldens():
    return load_literals("test_fowt.py", GOLDEN_NAMES)


def _create_fowt(path):
    with open(path) as f:
        design = yaml.load(f, Loader=yaml.FullLoader)
    min_freq = design["settings"]["min_freq"]
    max_freq = design["settings"]["max_freq"]
    w = np.arange(min_freq, max_freq + 0.5 * min_freq, min_freq) * 2 * np.pi
    fowt = FOWT(design, w, depth=design["site"]["water_depth"])
    fowt.setPosition(np.zeros(6))
    fowt.calcStatics()
    return fowt


@pytest.fixture(scope="module", params=list(enumerate(LIST_FILES)), ids=[f[:-5] for f in LIST_FILES])
def index_and_fowt(request, ref_test_data):
    index, fname = request.param
    return index, fname, _create_fowt(os.path.join(ref_test_data, fname))


def test_statics(index_and_fowt, goldens):
    index, _, fowt = index_and_fowt
    assert_allclose(fowt.rCG, goldens["desired_rCG"][index], rtol=1e-05, atol=1e-3)
    assert_allclose(fowt.rCG_sub, goldens["desired_rCG_sub"][index], rtol=1e-05, atol=1e-3)
    assert_allclose(fowt.m_ballast, goldens["desired_m_ballast"][index], rtol=1e-05, atol=1e-3)
    assert_allclose(fowt.M_struc, goldens["desired_M_struc"][index], rtol=1e-05, atol=1e-3)
    assert_allclose(fowt.M_struc_sub, goldens["desired_M_struc_sub"][index], rtol=1e-05, atol=1e-3)
    assert_allclose(fowt.C_struc, goldens["desired_C_struc"][index], rtol=1e-05, atol=1e-3)
    assert_allclose(fowt.W_struc, goldens["desired_W_struc"][index], rtol=1e-05, atol=1e-3)
    assert_allclose(fowt.rCB, goldens["desired_rCB"][index], rtol=1e-05, atol=1e-3)
    assert_allclose(fowt.C_hydro, goldens["desired_C_hydro"][index], rtol=1e-05, atol=1e-3)
    assert_allclose(fowt.W_hydro, goldens["desired_W_hydro"][index], rtol=1e-05, atol=1e-3)


def test_hydro_constants(index_and_fowt, goldens):
    index, _, fowt = index_and_fowt
    fowt.calcHydroConstants()
    assert_allclose(fowt.A_hydro_morison, goldens["desired_A_hydro_morison"][index], rtol=1e-05, atol=1e-3)


def test_hydro_excitation(index_and_fowt, ref_test_data):
    index, fname, fowt = index_and_fowt
    with open(os.path.join(ref_test_data, fname.replace(".yaml", "_true_hydroExcitation.pkl")), "rb") as f:
        true_values = pickle.load(f)

    fowt.calcHydroConstants()
    it = 0
    for wave_heading in [0, 45, 90, 135, 180, 225, 270, 315, 360]:
        for wave_period in [5, 10, 15, 20]:
            for wave_height in [1, 2]:
                case = {"wave_heading": wave_heading, "wave_period": wave_period, "wave_height": wave_height}
                fowt.calcHydroExcitation(case, memberList=fowt.memberList)
                assert_allclose(
                    fowt.F_hydro_iner, true_values[it]["F_hydro_iner"], rtol=1e-05, atol=1e-3,
                    err_msg=f"excitation mismatch for case {case}",
                )
                it += 1


def test_hydro_linearization(index_and_fowt, ref_test_data):
    index, fname, fowt = index_and_fowt
    fowt.calcHydroConstants()
    case = {"wave_spectrum": "unit", "wave_heading": 0, "wave_period": 10, "wave_height": 2}
    fowt.calcHydroExcitation(case, memberList=fowt.memberList)

    phase_array = np.linspace(0, 2 * np.pi, fowt.nw * 6).reshape(6, fowt.nw)
    Xi = 0.1 * np.exp(1j * phase_array)
    B_hydro_drag = fowt.calcHydroLinearization(Xi)
    F_hydro_drag = fowt.calcDragExcitation(0)

    with open(os.path.join(ref_test_data, fname.replace(".yaml", "_true_hydroLinearization.pkl")), "rb") as f:
        true_values = pickle.load(f)
    assert_allclose(B_hydro_drag, true_values["B_hydro_drag"], rtol=1e-05, atol=1e-10)
    assert_allclose(F_hydro_drag, true_values["F_hydro_drag"], rtol=1e-05)


def test_current_loads(index_and_fowt, goldens):
    index, _, fowt = index_and_fowt
    D = fowt.calcCurrentLoads({"current_speed": 2.0, "current_heading": 15})
    assert_allclose(D, goldens["desired_current_drag"][index], rtol=1e-05, atol=1e-3)
