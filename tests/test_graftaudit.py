"""graftaudit: IR-level static audit of the compiled sweep programs.

Three layers under test:

1. the :mod:`raft_tpu.analysis.hlo` parsers against real StableHLO /
   optimized-HLO text from tiny jitted programs (never synthetic-only —
   the spellings are the contract with the backend);
2. every audit rule catches a deliberately injected violation of its
   class — a forced reshard (shard_map psum), an un-donated buffer, an
   f64 promotion, an oversized captured constant, a memory budget
   breach — and stays quiet on the clean variant;
3. the live plumbing: compile-service / gather hooks, `audit_finding`
   ledger events + the `raft_audit_findings_total` metric, the
   graftaudit.toml ratchet, and the zero-overhead pin — auditing a cold
   sweep adds ZERO XLA compiles and leaves every result array
   bit-identical.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from raft_tpu.analysis import graftaudit, hlo
from raft_tpu.designs import demo_spar
from raft_tpu.obs import ledger as obs_ledger
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import schema as obs_schema

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5]])]
STATES = [(4.0, 8.0)]


def _shard_map():
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # moved in newer jax
        from jax.experimental import shard_map as _sm

        return _sm.shard_map
    return shard_map


def _psum_program():
    """A jitted shard_map whose body psums over the mesh axis — the
    exact shape of an accidental reshard/replication in the sweep."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("design",))
    f = _shard_map()(lambda x: jax.lax.psum(x, "design"), mesh=mesh,
                     in_specs=P("design"), out_specs=P(),
                     check_rep=False)
    lowered = jax.jit(f).lower(jnp.arange(8.0, dtype=jnp.float32))
    return lowered, lowered.compile()


# ---------------------------------------------------------------------------
# hlo parsers against real program text
# ---------------------------------------------------------------------------


def test_collective_counts_both_dialects_and_partitions():
    lowered, compiled = _psum_program()
    for text in (lowered.as_text(), compiled.as_text()):
        counts = hlo.collective_counts(text)
        assert counts.get("all-reduce", 0) >= 1, counts
        assert hlo.num_partitions(text) == 8
    # a collective-free program reports neither partitions nor ops
    clean = jax.jit(lambda x: x + 1.0).lower(jnp.zeros(4))
    assert hlo.collective_counts(clean.as_text()) == {}
    assert hlo.num_partitions(clean.as_text()) == 1


def test_hlo_done_halves_not_double_counted():
    text = ('  %ar0 = all-reduce-start(f32[8] %p0), replica_groups={}\n'
            '  %ar1 = all-reduce-done(f32[8] %ar0)\n')
    assert hlo.collective_counts(text) == {"all-reduce": 1}


def test_donation_markers_and_realized_aliases():
    f = jax.jit(lambda x: x * 2.0 + 1.0, donate_argnums=0)
    lowered = f.lower(jnp.zeros((256,), jnp.float32))
    assert hlo.donated_params(lowered.as_text()) == 1
    aliases = hlo.input_output_aliases(lowered.compile().as_text())
    assert len(aliases) == 1 and aliases[0][1] == 0, aliases
    # the un-donated twin carries neither marker nor alias
    g = jax.jit(lambda x: x * 2.0 + 1.0)
    glow = g.lower(jnp.zeros((256,), jnp.float32))
    assert hlo.donated_params(glow.as_text()) == 0
    assert hlo.input_output_aliases(glow.compile().as_text()) == []


def test_alias_parser_brace_scan_multi_entry():
    text = ("HloModule m, input_output_alias={ {0}: (0, {}, may-alias), "
            "{1}: (2, {}, must-alias) }, entry_computation_layout=...")
    got = hlo.input_output_aliases(text)
    assert [(a[1], a[2]) for a in got] == [(0, "may-alias"),
                                          (2, "must-alias")]


def test_wide_dtype_counts_partition_f64_and_c128():
    text = ("%0 = stablehlo.constant dense<1.0> : tensor<4xf64>\n"
            "%1 = stablehlo.multiply %a, %b : tensor<2xcomplex<f64>>\n")
    counts = hlo.wide_dtype_counts(text)
    assert counts == {"f64": 1, "c128": 1}


def test_large_constants_parse_and_threshold():
    big = np.arange(65536, dtype=np.float32)  # 256 KiB
    f = jax.jit(lambda x: x + jnp.asarray(big))
    text = f.lower(jnp.zeros(65536, jnp.float32)).as_text()
    found = hlo.large_constants(text, 1 << 10)
    assert found and found[0][0] == 65536 * 4
    assert "65536xf32" in found[0][1]
    assert hlo.large_constants(text, (1 << 20)) == []  # under 1 MiB


def test_memory_stats_fields_and_peak():
    compiled = jax.jit(lambda x: x * 2.0).lower(
        jnp.zeros((128,), jnp.float32)).compile()
    stats = hlo.memory_stats(compiled)
    assert stats is not None
    assert stats["peak_estimate"] == (
        stats.get("argument_size_in_bytes", 0)
        + stats.get("output_size_in_bytes", 0)
        + stats.get("temp_size_in_bytes", 0)
        - stats.get("alias_size_in_bytes", 0))


# ---------------------------------------------------------------------------
# audit rules: one injected violation per class
# ---------------------------------------------------------------------------


def test_ga_collective_catches_forced_reshard():
    lowered, compiled = _psum_program()
    res = graftaudit.audit_program(
        "p", stablehlo_text=lowered.as_text(), compiled=compiled,
        allow_wide=True)
    assert res.program == "p@8"
    hits = [f for f in res.findings if f.rule == "GA-COLLECTIVE"]
    assert hits and "all-reduce" in hits[0].detail
    # the same op declared expected is no finding
    spec = graftaudit.AuditSpec(
        expect_collectives={"p@8": ["all-reduce"]})
    res2 = graftaudit.audit_program(
        "p", stablehlo_text=lowered.as_text(), compiled=compiled,
        spec=spec, allow_wide=True)
    assert not [f for f in res2.findings if f.rule == "GA-COLLECTIVE"]


def test_ga_donation_catches_unrealized_and_floor():
    donated = jax.jit(lambda x: x * 2.0 + 1.0, donate_argnums=0)
    dlow = donated.lower(jnp.zeros((256,), jnp.float32))
    undonated = jax.jit(lambda x: x * 2.0 + 1.0)
    ulow = undonated.lower(jnp.zeros((256,), jnp.float32))
    utext = ulow.compile().as_text()

    # donated intent + a compiled module that aliased nothing -> finding
    res = graftaudit.audit_program(
        "k", stablehlo_text=dlow.as_text(), compiled_text=utext,
        allow_wide=True)
    assert [f.rule for f in res.findings] == ["GA-DONATION"]
    # realized donation is clean
    res_ok = graftaudit.audit_program(
        "k", stablehlo_text=dlow.as_text(),
        compiled_text=dlow.compile().as_text(), allow_wide=True)
    assert not res_ok.findings and res_ok.aliases == 1
    # an [expect.donation] floor catches a silently dropped donation
    spec = graftaudit.AuditSpec(expect_donation={"k@1": 1})
    res_floor = graftaudit.audit_program(
        "k", stablehlo_text=ulow.as_text(), compiled_text=utext,
        spec=spec, allow_wide=True)
    assert [f.rule for f in res_floor.findings] == ["GA-DONATION"]
    assert res_floor.findings[0].limit == 1


def test_ga_f64_catches_promotion_when_x64_off_for_audit():
    # tests run with x64 ON, so the audited program legitimately holds
    # f64 — allow_wide=False models the production (x64-off) audit
    f = jax.jit(lambda x: x * 2.0)
    text = f.lower(jnp.zeros(8, jnp.float64)).as_text()
    res = graftaudit.audit_program("k", stablehlo_text=text,
                                   allow_wide=False)
    assert [f_.rule for f_ in res.findings] == ["GA-F64"]
    # the default reads jax_enable_x64 (True here) and skips the rule
    res_default = graftaudit.audit_program("k", stablehlo_text=text)
    assert not [f_ for f_ in res_default.findings if f_.rule == "GA-F64"]


def test_ga_constant_catches_captured_array():
    big = np.arange(65536, dtype=np.float32)  # 256 KiB
    f = jax.jit(lambda x: x + jnp.asarray(big))
    text = f.lower(jnp.zeros(65536, jnp.float32)).as_text()
    spec = graftaudit.AuditSpec(constant_bytes=1 << 10)
    res = graftaudit.audit_program("k", stablehlo_text=text, spec=spec,
                                   allow_wide=True)
    hits = [f_ for f_ in res.findings if f_.rule == "GA-CONSTANT"]
    assert hits and hits[0].value == 65536 * 4
    # the default 1 MiB threshold lets the same program pass
    res_ok = graftaudit.audit_program("k", stablehlo_text=text,
                                      allow_wide=True)
    assert not res_ok.findings


def test_ga_memory_catches_budget_breach():
    compiled = jax.jit(lambda x: x * 2.0).lower(
        jnp.zeros((1024,), jnp.float32)).compile()
    spec = graftaudit.AuditSpec(budget={"test:k@1": 1})
    res = graftaudit.audit_program("k", compiled=compiled, spec=spec,
                                   budget_profile="test", allow_wide=True)
    hits = [f for f in res.findings if f.rule == "GA-MEMORY"]
    assert hits and hits[0].limit == 1 and hits[0].value > 1
    # no profile selected -> budgets do not apply
    res_off = graftaudit.audit_program("k", compiled=compiled, spec=spec,
                                       allow_wide=True)
    assert not res_off.findings


# ---------------------------------------------------------------------------
# baseline / budget ratchet (graftaudit.toml)
# ---------------------------------------------------------------------------


def test_diff_baseline_over_and_loosened():
    over, loosened = graftaudit.diff_baseline(
        {"B@8:GA-COLLECTIVE": 2, "A@1:GA-F64": 1},
        {"B@8:GA-COLLECTIVE": 1, "gone@1:GA-CONSTANT": 3})
    assert over == [("A@1:GA-F64", 1, 0), ("B@8:GA-COLLECTIVE", 2, 1)]
    assert loosened == [("gone@1:GA-CONSTANT", 0, 3)]


def test_write_spec_roundtrip_and_budget_ratchets_down_only(tmp_path):
    path = str(tmp_path / "graftaudit.toml")
    spec = graftaudit.AuditSpec(
        constant_bytes=2048, memory_headroom=1.5,
        expect_collectives={"B@8": ["all-gather"]},
        expect_donation={"B@1": 2},
        budget={"demo:B@1": 100})
    graftaudit.write_spec(path, spec, {"B@8:GA-COLLECTIVE": 1})
    spec2 = graftaudit.load_spec(path)
    assert spec2.constant_bytes == 2048
    assert spec2.memory_headroom == 1.5
    assert spec2.expect_collectives == {"B@8": ["all-gather"]}
    assert spec2.expect_donation == {"B@1": 2}
    assert spec2.budget == {"demo:B@1": 100}
    assert spec2.baseline == {"B@8:GA-COLLECTIVE": 1}

    # budgets: existing entries only ever go DOWN; missing entries are
    # seeded at peak * headroom
    results = [
        graftaudit.AuditResult(program="B@1",
                               memory={"peak_estimate": 1000}),
        graftaudit.AuditResult(program="A@1",
                               memory={"peak_estimate": 10}),
    ]
    graftaudit.write_spec(path, spec2, {}, results=results,
                          budget_profile="demo")
    spec3 = graftaudit.load_spec(path)
    assert spec3.budget["demo:B@1"] == 100     # 1500 proposed, kept low
    assert spec3.budget["demo:A@1"] == 15      # seeded at 10 * 1.5
    assert spec3.baseline == {}

    # a smaller measured peak ratchets the existing entry down
    graftaudit.write_spec(
        path, spec3, {},
        results=[graftaudit.AuditResult(program="B@1",
                                        memory={"peak_estimate": 20})],
        budget_profile="demo")
    assert graftaudit.load_spec(path).budget["demo:B@1"] == 30


def test_find_config_path_env_override(tmp_path, monkeypatch):
    cfg = tmp_path / "custom.toml"
    cfg.write_text("[audit]\nconstant_bytes = 7\n")
    monkeypatch.setenv("RAFT_TPU_AUDIT_CONFIG", str(cfg))
    assert graftaudit.find_config_path() == str(cfg)
    assert graftaudit.load_spec(graftaudit.find_config_path()
                                ).constant_bytes == 7
    monkeypatch.setenv("RAFT_TPU_AUDIT_CONFIG", "")
    # falls through to the repo-root graftaudit.toml
    got = graftaudit.find_config_path()
    assert got is None or os.path.basename(got) == "graftaudit.toml"


def test_repo_config_pins_shard_local_contract():
    """The checked-in graftaudit.toml must keep the canonical sweep
    programs collective-free (empty expected sets) and carry demo
    budgets for the CI-audited shapes."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = graftaudit.load_spec(os.path.join(root, "graftaudit.toml"))
    for prog in ("A@1", "B@1", "gather@1", "A@8", "B@8", "gather@8"):
        assert spec.expect_collectives.get(prog) == [], prog
    for key in ("demo:A@1", "demo:B@1", "demo:A@8", "demo:B@8",
                "bench:A@1", "bench:B@1"):
        assert spec.budget.get(key, 0) > 0, key
    assert spec.baseline == {}


# ---------------------------------------------------------------------------
# ledger events + metric
# ---------------------------------------------------------------------------


def test_record_emits_schema_valid_event_and_metric(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path / "ledger"))
    monkeypatch.setenv("RAFT_TPU_METRICS", "1")  # run latches this knob
    run = obs_ledger.start_run("audit-unit")
    finding = graftaudit.Finding("X@1", "GA-MEMORY", "over budget",
                                 value=10, limit=5)
    res = graftaudit.AuditResult(program="X@1", findings=[finding])
    graftaudit._record(res, run=run)
    run.finish(ok=True)
    events = obs_ledger.read_events(run.path)
    audit = [e for e in events if e.get("event") == "audit_finding"]
    assert len(audit) == 1
    ev = audit[0]
    assert (ev["program"], ev["rule"]) == ("X@1", "GA-MEMORY")
    assert (ev["value"], ev["limit"]) == (10, 5)
    assert not obs_schema.validate_event(ev)
    # the run-attached path counts through the metrics event observer
    assert obs_metrics.std().audit_findings.value(rule="GA-MEMORY") >= 1
    # session collector drained exactly once
    got = graftaudit.take_results()
    assert res in got and graftaudit.take_results() == []


def test_record_without_run_increments_metric_directly(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_METRICS", "1")
    before = obs_metrics.std().audit_findings.value(rule="GA-F64")
    res = graftaudit.AuditResult(
        program="Y@1",
        findings=[graftaudit.Finding("Y@1", "GA-F64", "wide")])
    graftaudit._record(res, run=None)
    assert obs_metrics.std().audit_findings.value(rule="GA-F64") == before + 1
    graftaudit.take_results()


def test_observe_program_never_raises_on_garbage():
    class Broken:
        def as_text(self):
            raise RuntimeError("boom")

        def memory_analysis(self):
            raise RuntimeError("boom")

    assert graftaudit.observe_program("bad", None, Broken(), Broken()) == []
    graftaudit.take_results()


# ---------------------------------------------------------------------------
# live integration: hooks, CLI, zero-overhead pin
# ---------------------------------------------------------------------------


def _bit_identical(a, b):
    for k in ("motion_std", "AxRNA_std", "mass", "displacement", "GMT",
              "status"):
        x, y = np.asarray(a[k]), np.asarray(b[k])
        assert x.dtype == y.dtype, (k, x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y, err_msg=k)


@pytest.mark.sentinel
def test_audit_on_zero_extra_compiles_bit_identical_and_ledger(
        tmp_path, monkeypatch):
    """THE acceptance pin: auditing a cold sweep adds ZERO XLA backend
    compiles (the audit only reads text/stats already in hand; the
    gather hook lowers without compiling), leaves every result array
    bit-identical, and an injected [expect.donation] floor violation
    flows through to `audit_finding` ledger events + the metric."""
    from raft_tpu import sweep as sweep_mod
    from raft_tpu.analysis.recompile import RecompileSentinel

    design = demo_spar(nw_freqs=(0.05, 0.4))
    dev = jax.devices()[0]
    kw = dict(n_iter=6, chunk_size=2, device=dev)

    # warm-up: eager-op and selector compiles cached for both runs
    sweep_mod.sweep(design, AXES, STATES, **kw)

    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path / "ledger-off"))
    sweep_mod._TEMPLATE_MEMO.clear()
    with RecompileSentinel() as s_off:
        base = sweep_mod.sweep(design, AXES, STATES, **kw)
    off_compiles = s_off.backend_compiles

    # impossible donation floor -> every audited program yields a finding
    cfg = tmp_path / "audit.toml"
    cfg.write_text("[expect.donation]\n"
                   '"A@1" = 999\n"B@1" = 999\n')
    monkeypatch.setenv("RAFT_TPU_AUDIT_CONFIG", str(cfg))
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path / "ledger-on"))
    monkeypatch.setenv("RAFT_TPU_METRICS", "1")
    before = obs_metrics.std().audit_findings.value(rule="GA-DONATION")
    sweep_mod._TEMPLATE_MEMO.clear()
    with RecompileSentinel() as s_on:
        with graftaudit.collecting():
            graftaudit.take_results()
            audited = sweep_mod.sweep(design, AXES, STATES, **kw)
            results = graftaudit.take_results()

    assert s_on.backend_compiles == off_compiles, (
        s_on.backend_compiles, off_compiles)
    _bit_identical(base, audited)

    # both chunk executables and the gather selector were audited
    names = {r.program for r in results}
    assert {"A@1", "B@1", "gather@1"} <= names, names
    for r in results:
        if r.program in ("A@1", "B@1"):
            assert r.findings
            assert all(f.rule == "GA-DONATION" for f in r.findings)

    # findings surfaced as ledger events + metric
    runs = obs_ledger.list_runs(str(tmp_path / "ledger-on"))
    events = obs_ledger.read_events(runs[-1])
    audit_events = [e for e in events if e.get("event") == "audit_finding"]
    assert {e["program"] for e in audit_events} == {"A@1", "B@1"}
    assert all(e["rule"] == "GA-DONATION" for e in audit_events)
    assert (obs_metrics.std().audit_findings.value(rule="GA-DONATION")
            >= before + 2)


def test_env_armed_audit_and_off_path_untouched(monkeypatch):
    """RAFT_TPU_AUDIT=1 arms the hooks without a collecting() context;
    unset, a warm sweep records nothing (the off path never imports or
    runs the auditor)."""
    from raft_tpu import sweep as sweep_mod
    from raft_tpu.parallel.compile_service import _audit_armed

    monkeypatch.delenv("RAFT_TPU_AUDIT", raising=False)
    assert not _audit_armed()
    design = demo_spar(nw_freqs=(0.05, 0.4))
    dev = jax.devices()[0]
    graftaudit.take_results()
    sweep_mod.sweep(design, AXES, STATES, n_iter=6, chunk_size=2,
                    device=dev)
    assert graftaudit.take_results() == []

    monkeypatch.setenv("RAFT_TPU_AUDIT", "1")
    assert _audit_armed()
    # warm repeat: the memoized executables skip the compile service,
    # but the gather selector is still audited every sweep
    sweep_mod.sweep(design, AXES, STATES, n_iter=6, chunk_size=2,
                    device=dev)
    results = graftaudit.take_results()
    assert {r.program for r in results} == {"gather@1"}
    assert not results[0].findings
    assert results[0].collectives == {}


def test_cli_reports_injected_finding_and_baseline_gate(
        tmp_path, monkeypatch, capsys):
    """CLI end-to-end on a pre-seeded exec-cache-free path: a config
    whose [baseline] absorbs an injected finding exits 0; without the
    baseline the same finding fails the run and lands in the JSON
    report."""
    lowered, compiled = _psum_program()
    monkeypatch.setattr(
        graftaudit, "audit_live_plan",
        lambda *a, **k: [graftaudit.audit_program(
            "p", stablehlo_text=lowered.as_text(), compiled=compiled,
            spec=k.get("spec"), allow_wide=True)])

    report = str(tmp_path / "report.json")
    cfg = tmp_path / "audit.toml"
    cfg.write_text("")
    rc = graftaudit.main(["--demo", "--config", str(cfg),
                          "--report", report])
    out = capsys.readouterr().out
    assert rc == 1
    assert "GA-COLLECTIVE" in out and "p@8" in out
    payload = json.load(open(report))
    assert payload["over_baseline"]
    assert payload["programs"][0]["collectives"] == {"all-reduce": 1}

    # baselining the finding makes the same audit pass...
    cfg.write_text('[baseline]\n"p@8:GA-COLLECTIVE" = 1\n')
    assert graftaudit.main(["--demo", "--config", str(cfg)]) == 0
    capsys.readouterr()
    # ...and --no-baseline reports it again
    assert graftaudit.main(["--demo", "--config", str(cfg),
                            "--no-baseline"]) == 1
    assert "GA-COLLECTIVE" in capsys.readouterr().out


def test_cli_update_baseline_writes_ratchet(tmp_path, monkeypatch, capsys):
    lowered, compiled = _psum_program()
    monkeypatch.setattr(
        graftaudit, "audit_live_plan",
        lambda *a, **k: [graftaudit.audit_program(
            "p", stablehlo_text=lowered.as_text(), compiled=compiled,
            spec=k.get("spec"), allow_wide=True)])
    cfg = tmp_path / "audit.toml"
    cfg.write_text("")
    rc = graftaudit.main(["--demo", "--config", str(cfg),
                          "--update-baseline"])
    capsys.readouterr()
    assert rc == 0
    spec = graftaudit.load_spec(str(cfg))
    assert spec.baseline == {"p@8:GA-COLLECTIVE": 1}
    # budgets seeded from the audited program's memory stats
    assert spec.budget.get("demo:p@8", 0) > 0
    # the baselined finding now passes the plain run
    assert graftaudit.main(["--demo", "--config", str(cfg)]) == 0
    capsys.readouterr()


def test_exec_cache_audit(tmp_path, monkeypatch):
    """Serialized executables audit from their compiled side: a cached
    psum program is flagged for its collective; backend-mismatched and
    corrupt entries are skipped with reasons, never fatal."""
    import pickle

    from raft_tpu.obs import ledger as _led
    from raft_tpu.parallel import compile_service as cs

    cache = tmp_path / "exec-cache"
    cfg = {"service": False, "workers": 1, "exec_cache": str(cache)}
    lowered, _ = _psum_program()
    task = cs.CompileService(run=_led.NULL_RUN, config=cfg).submit(
        "p", lowered, cache_tag="audit-test")
    task.wait()
    entries = [n for n in os.listdir(cache) if n.endswith(".jexec")]
    assert entries

    # corrupt entry + backend-mismatched entry ride along
    (cache / "corrupt.jexec").write_bytes(b"not a pickle")
    with open(cache / entries[0], "rb") as fh:
        entry = pickle.load(fh)
    entry["meta"] = dict(entry["meta"], backend="tpu-v9")
    with open(cache / "othergen.jexec", "wb") as fh:
        pickle.dump(entry, fh)

    results, skipped = graftaudit.audit_exec_cache(str(cache))
    assert len(results) == 1 and results[0].source == "exec_cache"
    assert results[0].program == "p@8"
    assert [f.rule for f in results[0].findings] == ["GA-COLLECTIVE"]
    reasons = {n: why for n, why in skipped}
    assert "corrupt.jexec" in reasons
    assert "othergen.jexec" in reasons and "backend" in reasons["othergen.jexec"]
