"""Cross-run history store + perf-regression gate (raft_tpu.obs.history).

Synthetic ledgers (the same event vocabulary real runs emit, with
controlled timings) drive the ingest -> compare -> check pipeline:
the gate must fail on an injected regression (inflated chunk times /
wall clock), pass within tolerance, pass vacuously when no prior run
matches the fingerprint, and enforce absolute --require constraints
regardless (the CI exec-cache real_compiles<=0 pin).
"""

import json
import os
import subprocess
import sys

import pytest

from raft_tpu.obs import history as obs_history


def _mk_ledger(path, run_id, *, chunk_s=(1.0, 1.0), wall_s=10.0,
               real_compiles=0, fingerprint=None, kind="sweep", ok=True):
    """Write one synthetic (schema-shaped) ledger file."""
    fingerprint = fingerprint if fingerprint is not None else {
        "design": "abc", "n_designs": 4, "n_cases": 2}
    t0 = 1000.0
    events = [{"t": t0, "seq": 1, "event": "run_start",
               "run_id": run_id, "kind": kind, "fingerprint": fingerprint}]
    seq = 2

    def add(event, dt, **fields):
        nonlocal seq
        events.append({"t": t0 + dt, "seq": seq, "event": event, **fields})
        seq += 1

    add("plan", 0.1, mode="resident", n_chunks=len(chunk_s), chunk_size=2)
    for i in range(real_compiles):
        add("compile_start", 0.2 + i * 0.01, key=f"part{i}", real=True)
        add("compile_end", 1.0 + i * 0.01, key=f"part{i}", cache="miss",
            seconds=0.8)
    t = 1.5
    done = 0
    for c, dur in enumerate(chunk_s):
        add("chunk_dispatch", t, chunk=c, start=c * 2, stop=c * 2 + 2,
            n_real=2, in_flight=1)
        add("chunk_fetch", t + dur * 0.8, chunk=c, bytes=4096)
        done += 2
        add("chunk_commit", t + dur, chunk=c, done=done,
            n_designs=2 * len(chunk_s), eta_s=0.0)
        t += dur
    add("phase_stats", wall_s - 0.2, name="sweep/chunks", calls=1,
        total=round(sum(chunk_s), 6), min=min(chunk_s),
        mean=sum(chunk_s) / len(chunk_s), max=max(chunk_s))
    add("run_end", wall_s, ok=ok, counts={"ok": done})
    with open(path, "w", encoding="utf-8") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")
    return str(path)


def test_summarize_ledger_derives_metrics(tmp_path):
    p = _mk_ledger(tmp_path / "a.jsonl", "run-a", chunk_s=(1.0, 2.0),
                   wall_s=12.0, real_compiles=2)
    rec = obs_history.summarize_ledger(p)
    assert rec["run_id"] == "run-a" and rec["kind"] == "sweep"
    assert rec["ok"] is True and rec["fp_key"]
    m = rec["metrics"]
    assert m["wall_s"] == pytest.approx(12.0)
    assert m["real_compiles"] == 2
    assert m["chunks_committed"] == 2
    assert m["chunk_mean_s"] == pytest.approx(1.5)
    assert m["chunk_max_s"] == pytest.approx(2.0)
    assert m["compile_total_s"] == pytest.approx(1.6)
    assert m["d2h_bytes"] == 8192
    assert rec["chunk_seconds"] == [pytest.approx(1.0), pytest.approx(2.0)]
    assert rec["phase_totals"]["sweep/chunks"] == pytest.approx(3.0)


def test_ingest_is_append_only_and_deduplicated(tmp_path):
    store = str(tmp_path / "history.jsonl")
    a = _mk_ledger(tmp_path / "a.jsonl", "run-a")
    b = _mk_ledger(tmp_path / "b.jsonl", "run-b")
    assert obs_history.ingest_paths(store, [a, b]) == 2
    # re-ingest: nothing new (dedup on run_id)
    assert obs_history.ingest_paths(store, [a, b]) == 0
    records = obs_history.load_store(store)
    assert [r["run_id"] for r in records] == ["run-a", "run-b"]
    # a directory of ledgers ingests too
    (tmp_path / "more").mkdir()
    _mk_ledger(tmp_path / "more" / "c.jsonl", "run-c")
    assert obs_history.ingest_paths(store, [str(tmp_path / "more")]) == 1


def test_ingest_bench_history_jsonl(tmp_path):
    store = str(tmp_path / "history.jsonl")
    bench = tmp_path / "bench_history.jsonl"
    line = {"metric": "1000-design sweep", "value": 30.2, "unit": "s",
            "t": 1234.5,
            "detail": {"repeat_sweep_s": 3.1, "repeat_xla_compiles": 0,
                       "repeat_phases_s": {"chunks": 2.9}}}
    bench.write_text(json.dumps(line) + "\n" + json.dumps(line) + "\n")
    assert obs_history.ingest_paths(store, [str(bench)]) >= 1
    rec = obs_history.load_store(store)[0]
    assert rec["source"] == "bench" and rec["kind"] == "bench"
    assert rec["metrics"]["wall_s"] == pytest.approx(30.2)
    assert rec["metrics"]["real_compiles"] == 0
    assert rec["phase_totals"]["chunks"] == pytest.approx(2.9)


def test_compare_reports_metric_phase_chunk_deltas(tmp_path):
    old = obs_history.summarize_ledger(
        _mk_ledger(tmp_path / "a.jsonl", "run-a", chunk_s=(1.0, 1.0),
                   wall_s=10.0))
    new = obs_history.summarize_ledger(
        _mk_ledger(tmp_path / "b.jsonl", "run-b", chunk_s=(1.5, 2.5),
                   wall_s=14.0))
    cmp = obs_history.compare_records(old, new)
    assert cmp["metrics"]["wall_s"]["delta"] == pytest.approx(4.0)
    assert cmp["metrics"]["wall_s"]["ratio"] == pytest.approx(1.4)
    assert cmp["phases"]["sweep/chunks"]["delta"] == pytest.approx(2.0)
    assert cmp["chunks"]["n_compared"] == 2
    assert cmp["chunks"]["per_chunk_delta_s"] == [
        pytest.approx(0.5), pytest.approx(1.5)]
    assert cmp["chunks"]["max_delta_s"] == pytest.approx(1.5)


def _store_with(tmp_path, specs):
    """Ingest a sequence of synthetic ledgers; return the store path."""
    store = str(tmp_path / "history.jsonl")
    paths = []
    for i, kw in enumerate(specs):
        paths.append(_mk_ledger(tmp_path / f"r{i}.jsonl", f"run-{i}", **kw))
    assert obs_history.ingest_paths(store, paths) == len(specs)
    return store


def test_check_fails_on_injected_regression(tmp_path):
    """ISSUE acceptance: nonzero exit on a synthetic ledger with
    inflated chunk times vs the rolling baseline."""
    store = _store_with(tmp_path, [
        {"chunk_s": (1.0, 1.0), "wall_s": 10.0},
        {"chunk_s": (1.1, 0.9), "wall_s": 10.2},
        {"chunk_s": (2.5, 2.5), "wall_s": 21.0},  # newest: 2x regression
    ])
    rc = obs_history.main(["check", "--store", store, "--tolerance", "0.25"])
    assert rc == 1
    result = obs_history.run_check(obs_history.load_store(store),
                                   tolerance=0.25)
    assert not result["ok"]
    failed = {c["metric"] for c in result["checks"] if not c["ok"]}
    assert {"wall_s", "chunk_mean_s"} <= failed
    assert len(result["baseline_runs"]) == 2


def test_check_passes_within_tolerance(tmp_path):
    store = _store_with(tmp_path, [
        {"chunk_s": (1.0, 1.0), "wall_s": 10.0},
        {"chunk_s": (1.05, 1.05), "wall_s": 10.8},  # +8% < 25% tolerance
    ])
    assert obs_history.main(["check", "--store", store]) == 0


def test_check_passes_with_no_matching_fingerprint(tmp_path):
    """A new workload has no baseline: the relative gate is vacuous
    (exit 0), it must not compare apples to oranges."""
    store = _store_with(tmp_path, [
        {"fingerprint": {"design": "aaa", "n_designs": 4}},
        {"fingerprint": {"design": "bbb", "n_designs": 1000},
         "chunk_s": (9.0, 9.0), "wall_s": 99.0},
    ])
    rc = obs_history.main(["check", "--store", store])
    assert rc == 0
    result = obs_history.run_check(obs_history.load_store(store))
    assert result["ok"] and result["baseline_runs"] == []
    assert any("no prior record matches" in n for n in result["notes"])


def test_check_requires_are_absolute(tmp_path):
    """--require constraints bind even without a baseline (the CI
    exec-cache pin: the warm run must show zero real compiles)."""
    store = _store_with(tmp_path, [{"real_compiles": 2}])
    assert obs_history.main(
        ["check", "--store", store, "--require", "real_compiles<=0"]) == 1
    assert obs_history.main(
        ["check", "--store", store, "--require", "real_compiles<=2"]) == 0
    # malformed expressions are a usage error, not a silent pass
    with pytest.raises(ValueError):
        obs_history.parse_require("real_compiles !! 0")


def test_check_empty_store_is_clean(tmp_path):
    store = str(tmp_path / "empty.jsonl")
    assert obs_history.main(["check", "--store", store]) == 0


def test_list_and_compare_cli(tmp_path, capsys):
    store = _store_with(tmp_path, [
        {"chunk_s": (1.0, 1.0)}, {"chunk_s": (1.2, 1.2)}])
    assert obs_history.main(["list", "--store", store]) == 0
    out = capsys.readouterr().out
    assert "run-0" in out and "run-1" in out and "wall_s" in out
    assert obs_history.main(["compare", "--store", store]) == 0
    out = capsys.readouterr().out
    assert "run-0" in out and "run-1" in out and "chunks" in out
    # explicit pair by run-id prefix, JSON output
    assert obs_history.main(
        ["compare", "--store", store, "run-0", "run-1", "--json"]) == 0
    cmp = json.loads(capsys.readouterr().out)
    assert cmp["old_run"] == "run-0" and cmp["new_run"] == "run-1"


@pytest.mark.slow
def test_cli_exit_code_through_real_process(tmp_path):
    """The gate's exit code must survive the real `python -m` boundary
    (what CI shells out to)."""
    store = _store_with(tmp_path, [
        {"chunk_s": (1.0, 1.0), "wall_s": 10.0},
        {"chunk_s": (3.0, 3.0), "wall_s": 25.0},
    ])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs.history", "check",
         "--store", store],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "FAIL" in proc.stdout
