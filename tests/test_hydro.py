"""Second-order hydro + WAMIT IO tests.

No reference golden exists for the slender-body QTF (the reference has
no test for it and can't run here), so these tests pin down structural
invariants and analytic identities, plus IO round-trips against the
reference's shipped marin_semi files.
"""

import os

import numpy as np
import pytest
import yaml

import raft_tpu
from raft_tpu.hydro import second_order as so
from raft_tpu.hydro import wamit_io

EXAMPLES = "/root/reference/examples"


@pytest.fixture(scope="module")
def oc4_qtf_model():
    with open(f"{EXAMPLES}/OC4semi-RAFT_QTF.yaml") as f:
        design = yaml.load(f, Loader=yaml.FullLoader)
    design["platform"]["outFolderQTF"] = None
    model = raft_tpu.Model(design)
    case = dict(zip(design["cases"]["keys"], design["cases"]["data"][0]))
    case["iCase"] = 0
    model.solveStatics(case)
    model.solveDynamics(case)
    return model


def test_qtf_structure(oc4_qtf_model):
    fowt = oc4_qtf_model.fowtList[0]
    q = fowt.qtf[:, :, 0, :]
    nw2 = len(fowt.w1_2nd)
    assert q.shape == (nw2, nw2, 6)
    for i in range(6):
        # Hermitian: Q(w2,w1) = conj(Q(w1,w2))
        assert np.allclose(q[:, :, i], np.conj(q[:, :, i]).T, atol=1e-12 * np.abs(q).max())
        # real diagonal (mean drift)
        assert np.max(np.abs(np.imag(np.diag(q[:, :, i])))) < 1e-9 * (np.abs(q).max() + 1)


def test_mean_drift_physical(oc4_qtf_model):
    """Head-sea mean surge drift on a semi-sub must be positive (downwave)
    and of plausible magnitude for Hs~6 m."""
    fowt = oc4_qtf_model.fowtList[0]
    Fm = fowt.Fhydro_2nd_mean[0]
    assert Fm[0] > 1e3  # surge drift downwave
    assert Fm[0] < 1e7
    assert abs(Fm[1]) < 0.01 * abs(Fm[0]) + 1.0  # symmetric: no sway drift


def test_second_order_forces_in_response(oc4_qtf_model):
    """The 2nd-order force must be finite and populate low frequencies."""
    fowt = oc4_qtf_model.fowtList[0]
    f2 = fowt.Fhydro_2nd[0]
    assert np.all(np.isfinite(f2))
    assert np.abs(f2).max() > 0


def test_12d_roundtrip(tmp_path, oc4_qtf_model):
    fowt = oc4_qtf_model.fowtList[0]
    path = str(tmp_path / "test.12d")
    fowt.heads_2nd = np.atleast_1d(fowt.heads_2nd)
    so.write_qtf(fowt, fowt.qtf, path)

    q_orig = fowt.qtf.copy()
    w1_orig = fowt.w1_2nd.copy()
    so.read_qtf(fowt, path)
    assert np.allclose(fowt.w1_2nd, w1_orig, rtol=1e-3)
    # compare on the upper triangle (write emits w2 >= w1 only)
    n = len(w1_orig)
    iu = np.triu_indices(n)
    for i in range(6):
        a = q_orig[:, :, 0, i][iu]
        b = fowt.qtf[:, :, 0, i][iu]
        keep = np.abs(a) > 1e-6 * np.abs(a).max()
        assert np.allclose(a[keep], b[keep], rtol=2e-3), i


def test_wamit1_reader():
    A, B, w = wamit_io.read_wamit1(f"{EXAMPLES}/OC4semi-WAMIT_Coefs/marin_semi.1")
    # file's first line: PER=628.319, (1,1) entry Abar=8527.234, Bbar=1.604159e-2
    i = np.argmin(np.abs(w - 2 * np.pi / 628.319))
    assert np.isclose(A[0, 0, i], 8527.234, rtol=1e-6)
    assert np.isclose(B[0, 0, i], 1.604159e-2, rtol=1e-6)
    assert w[0] == 0.0 and np.isinf(w[1])


def test_wamit3_reader(tmp_path):
    """Synthesized .3 file exercises the full excitation path."""
    path = str(tmp_path / "t.3")
    rows = []
    for per in (10.0, 5.0):
        for head in (0.0, 90.0):
            for dof in range(1, 7):
                re, im = dof * 1.0, -dof * 0.5
                mod, pha = np.hypot(re, im), np.arctan2(im, re)
                rows.append(f"{per} {head} {dof} {mod} {pha} {re} {im}")
    with open(path, "w") as f:
        f.write("\n".join(rows))
    M, P, R, I, w, heads = wamit_io.read_wamit3(path)
    assert M.shape == (2, 6, 2)
    assert np.allclose(w, [2 * np.pi / 10, 2 * np.pi / 5])
    assert np.allclose(R[0, :, 0], np.arange(1, 7))
    assert np.allclose(I[1, :, 1], -0.5 * np.arange(1, 7))


def test_hydro_force_2nd_analytic():
    """With a constant real QTF Q0 the mean drift is 2*Q0*sum(S)*dw."""

    class FakeFowt:
        pass

    f = FakeFowt()
    nw = 50
    f.nw = nw
    f.w = np.linspace(0.05, 2.5, nw)
    f.dw = f.w[1] - f.w[0]
    f.w1_2nd = np.linspace(0.05, 2.5, 25)
    f.heads_2nd = [0.0]
    Q0 = 123.0
    f.qtf = np.full([25, 25, 1, 6], Q0, dtype=complex)
    f.outFolderQTF = None

    S0 = np.exp(-((f.w - 0.8) ** 2) / 0.05)
    f_mean, famp = so.calc_hydro_force_2nd_ord(f, 0.0, S0)
    expected = 2 * Q0 * np.sum(S0) * f.dw
    assert np.allclose(f_mean, expected, rtol=1e-12)
    assert famp.shape == (6, nw)
    assert np.all(np.isfinite(famp))


def test_qtf_sequence_parallel_matches_single_device():
    """The (w1, w2) QTF plane sharded over a 'seq' device mesh (the
    sequence-parallel axis, SURVEY.md §5) reproduces the single-device
    result exactly."""
    import jax

    from raft_tpu.core.fowt import FOWT
    from raft_tpu.designs import demo_spar
    from raft_tpu.hydro import second_order as so

    design = demo_spar(nw_freqs=(0.05, 0.4))
    design["platform"]["potSecOrder"] = 1
    design["platform"]["min_freq2nd"] = 0.05
    design["platform"]["max_freq2nd"] = 0.35
    design["platform"]["df_freq2nd"] = 0.02
    w = np.arange(0.05, 0.4, 0.05) * 2 * np.pi
    fowt = FOWT(design, w, depth=320.0)
    fowt.setPosition(np.zeros(6))
    fowt.calcStatics()
    fowt.calcHydroConstants()
    case = dict(zip(design["cases"]["keys"], design["cases"]["data"][0]))
    fowt.calcHydroExcitation(case)
    rng = np.random.default_rng(3)
    Xi0 = rng.normal(size=(6, fowt.nw)) + 1j * rng.normal(size=(6, fowt.nw))

    q_single = so.calc_qtf_slender_body(fowt, 0, Xi0=Xi0).copy()
    fowt.qtf_seq_devices = jax.devices()[:8]
    try:
        q_sharded = so.calc_qtf_slender_body(fowt, 0, Xi0=Xi0).copy()
    finally:
        fowt.qtf_seq_devices = None
    assert len(jax.devices()) >= 8  # conftest forces the 8-device CPU mesh
    np.testing.assert_allclose(q_sharded, q_single, rtol=1e-12, atol=1e-9)
