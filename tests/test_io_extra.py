"""Coverage for the remaining io_utils surface and secondary model paths:
IEA-ontology turbine conversion, WAMIT .p2 reading, tower-base stress
PSD, mooring write-back, the 'spectrum' second-order force mode, and
preprocess_HAMS."""

import numpy as np
import pytest
import yaml

from raft_tpu import io_utils


def _minimal_windio(tmp_path):
    grid = [0.0, 0.5, 1.0]
    wt = {
        "assembly": {"number_of_blades": 3, "rotor_diameter": 0.0,
                     "hub_height": 150.0},
        "components": {
            "hub": {"diameter": 7.0, "cone_angle": np.deg2rad(4.0).item()},
            "nacelle": {"drivetrain": {"uptilt": np.deg2rad(6.0).item(),
                                       "overhang": -12.0,
                                       "distance_tt_hub": 5.0}},
            "blade": {"outer_shape_bem": {
                "reference_axis": {
                    "x": {"grid": grid, "values": [0.0, -1.0, -4.0]},
                    "y": {"grid": grid, "values": [0.0, 0.0, 0.0]},
                    "z": {"grid": grid, "values": [0.0, 58.0, 117.0]},
                },
                "chord": {"grid": grid, "values": [5.2, 4.0, 1.0]},
                "twist": {"grid": grid,
                          "values": [np.deg2rad(15.0).item(), np.deg2rad(5.0).item(), 0.0]},
                "airfoil_position": {"grid": [0.0, 1.0], "labels": ["af1", "af1"]},
            }},
            "tower": {"outer_shape_bem": {"reference_axis": {
                "z": {"grid": grid, "values": [0.0, 70.0, 145.0]}}}},
        },
        "environment": {"air_density": 1.225},
        "airfoils": [{
            "name": "af1", "relative_thickness": 0.21,
            "polars": [{
                "c_l": {"grid": [-3.14, 0.0, 3.14], "values": [0.0, 0.8, 0.0]},
                "c_d": {"grid": [-3.14, 0.0, 3.14], "values": [0.5, 0.01, 0.5]},
                "c_m": {"grid": [-3.14, 0.0, 3.14], "values": [0.0, -0.1, 0.0]},
            }],
        }],
    }
    path = tmp_path / "iea_turbine.yaml"
    path.write_text(yaml.safe_dump(wt))
    return str(path)


def test_convert_iea_turbine_yaml(tmp_path):
    d = io_utils.convert_iea_turbine_yaml(_minimal_windio(tmp_path), n_span=10)
    assert d["nBlades"] == 3
    assert d["Rhub"] == pytest.approx(3.5)
    assert d["precone"] == pytest.approx(4.0)
    assert d["Zhub"] == pytest.approx(150.0)
    assert d["blade"]["Rtip"] == pytest.approx(117.0 + 3.5)
    assert len(d["blade"]["r"]) == 8           # interior span points
    assert len(d["airfoils"]) == 1
    tab = np.asarray(d["airfoils"][0]["data"])
    assert tab.shape[1] == 4                   # alpha, cl, cd, cm
    assert tab[:, 0].min() < -170 and tab[:, 0].max() > 170  # degrees


def test_read_wamit_p2(tmp_path):
    """Synthetic .p2: 2 periods x 2 headings x 6 DoF, WAMIT normalization."""
    rows = []
    for per in (5.0, 10.0):
        for hd in (0.0, 30.0):
            for dof in range(1, 7):
                re, im = dof * 0.1, -dof * 0.05
                rows.append([per, hd, dof, 0.0, 0.0, re, im])
    path = tmp_path / "out.p2"
    np.savetxt(path, np.array(rows))
    W2 = io_utils.read_wamit_p2(str(path), rho=1025.0, L=2.0, g=9.81)
    assert list(W2["period"]) == [5.0, 10.0]
    assert list(W2["heading"]) == [0.0, 30.0]
    # surge scales by rho*g*L^2, roll by rho*g*L^3
    assert W2["surge"][0, 0] == pytest.approx((0.1 - 0.05j) * 1025 * 9.81 * 4.0)
    assert W2["roll"][0, 0] == pytest.approx((0.4 - 0.2j) * 1025 * 9.81 * 8.0)


def test_tower_base_stress_psd():
    w = np.linspace(0.1, 2.0, 40)
    TBFA = np.exp(-(w - 0.8) ** 2) * 1e8      # fore-aft moment amplitudes
    TBSS = 0.5 * np.exp(-(w - 0.8) ** 2) * 1e8
    psd, ANG, FRQ = io_utils.tower_base_stress_psd(TBFA, TBSS, w)
    psd = np.asarray(psd)
    assert np.all(np.isfinite(psd))
    assert np.max(psd) > 0
    # reference quirk: one PSD value per circumferential angle
    assert psd.shape == (50,)


def test_adjust_mooring_roundtrip():
    from raft_tpu.designs import demo_spar
    from raft_tpu.mooring import system as moorsys

    design = demo_spar(nw_freqs=(0.05, 0.4))
    ms = moorsys.compile_mooring(design["mooring"])
    out = io_utils.adjust_mooring(ms, design)
    assert out["mooring"]["water_depth"] == pytest.approx(float(np.asarray(ms.params.depth)))
    assert out["mooring"]["lines"][0]["length"] == pytest.approx(
        float(np.asarray(ms.params.L)[0]))


def test_second_order_spectrum_mode():
    """calcHydroForce_2ndOrd interpMode='spectrum' vs 'qtf': same mean
    drift (both integrate the same QTF diagonal) and comparable slow-
    drift force scale."""
    import jax

    from raft_tpu.core.fowt import FOWT
    from raft_tpu.designs import demo_spar
    from raft_tpu.hydro import second_order as so
    from raft_tpu.ops import waves as waves_ops

    design = demo_spar(nw_freqs=(0.05, 0.4))
    design["platform"]["potSecOrder"] = 1
    design["platform"]["min_freq2nd"] = 0.05
    design["platform"]["max_freq2nd"] = 0.35
    design["platform"]["df_freq2nd"] = 0.05
    w = np.arange(0.05, 0.4, 0.05) * 2 * np.pi
    fowt = FOWT(design, w, depth=320.0)
    fowt.setPosition(np.zeros(6))
    fowt.calcStatics()
    fowt.calcHydroConstants()
    case = dict(zip(design["cases"]["keys"], design["cases"]["data"][0]))
    fowt.calcHydroExcitation(case)
    so.calc_qtf_slender_body(fowt, 0)

    S0 = np.asarray(waves_ops.jonswap(np.asarray(w), 6.0, 10.0))
    mean_q, f_q = so.calc_hydro_force_2nd_ord(fowt, 0.0, S0, interpMode="qtf")
    mean_s, f_s = so.calc_hydro_force_2nd_ord(fowt, 0.0, S0, interpMode="spectrum")
    assert np.all(np.isfinite(f_q)) and np.all(np.isfinite(f_s))
    # strongest mean-drift channel: same sign, same order in both modes
    idof = int(np.argmax(np.abs(mean_q)))
    assert mean_q[idof] != 0
    assert np.sign(mean_s[idof]) == np.sign(mean_q[idof])
    assert 0.1 < abs(mean_s[idof] / mean_q[idof]) < 10.0


def test_preprocess_hams_exports_mesh(tmp_path):
    import raft_tpu
    from raft_tpu.designs import demo_spar

    design = demo_spar(nw_freqs=(0.05, 0.3))
    design["platform"]["potModMaster"] = 0
    design["platform"]["members"][0]["potMod"] = True
    model = raft_tpu.Model(design)
    for fowt in model.fowtList:
        fowt.setPosition(np.zeros(6))
        fowt.calcStatics()
    model.preprocess_HAMS(dz=5.0, da=5.0, meshDir=str(tmp_path))
    assert (tmp_path / "HullMesh.pnl").exists()
