"""Member physics parity tests.

Runs the 10-geometry member matrix from the reference test corpus
(tests/test_member.py in /root/reference — {surface-piercing, submerged} ×
{vertical, pitched, inclined, horizontal, tapered} × {circular,
rectangular}) through the compiled-member kernels and compares against
the reference's inline golden values.
"""

import numpy as np
import pytest
import yaml
from numpy.testing import assert_allclose

from raft_tpu.structure import member as M

from ref_goldens import load_literals

LIST_FILES = [
    "mem_srf_vert_circ_cyl.yaml",
    "mem_srf_vert_rect_cyl.yaml",
    "mem_srf_pitch_circ_cyl.yaml",
    "mem_srf_pitch_rect_cyl.yaml",
    "mem_srf_inc_circ_cyl.yaml",
    "mem_srf_inc_rect_cyl.yaml",
    "mem_subm_horz_circ_cyl.yaml",
    "mem_subm_horz_rect_cyl.yaml",
    "mem_srf_vert_tap_circ_cyl.yaml",
    "mem_srf_vert_tap_rect_cyl.yaml",
]


@pytest.fixture(scope="module")
def goldens(ref_test_data):
    return load_literals(
        "test_member.py",
        [
            "desired_inertiaBasic",
            "desired_inertiaMatrix",
            "desired_hydrostatics",
            "desired_Ahydro",
            "desired_Ihydro",
        ],
    )


def compile_from_yaml(ref_test_data, fname):
    with open(f"{ref_test_data}/{fname}") as f:
        design = yaml.load(f, Loader=yaml.FullLoader)
    cm = M.compile_member(design["members"][0])
    pose = M.member_pose(cm.topo, cm.geom)
    return cm, pose


@pytest.mark.parametrize("index", range(len(LIST_FILES)))
def test_inertia(index, ref_test_data, goldens):
    cm, pose = compile_from_yaml(ref_test_data, LIST_FILES[index])
    M_struc, mass, cg, mshell, mfill, pfill = M.member_inertia(cm.topo, cm.geom, pose)
    assert_allclose(
        [float(mshell), float(mfill[0]), float(cg[0]), float(cg[1]), float(cg[2])],
        goldens["desired_inertiaBasic"][index],
        rtol=1e-05,
        atol=1e-5,
    )
    assert_allclose(np.asarray(M_struc), goldens["desired_inertiaMatrix"][index], rtol=1e-05)


@pytest.mark.parametrize("index", range(len(LIST_FILES)))
def test_hydrostatics(index, ref_test_data, goldens):
    cm, pose = compile_from_yaml(ref_test_data, LIST_FILES[index])
    Fvec, Cmat, V_UW, r_center, AWP, IWP, xWP, yWP = M.member_hydrostatics(
        cm.topo, cm.geom, pose, rho=1025, g=9.81
    )
    got = [
        float(Fvec[2]),
        float(Fvec[3]),
        float(Fvec[4]),
        float(Cmat[2, 2]),
        float(Cmat[3, 3]),
        float(Cmat[4, 4]),
        float(r_center[0]),
        float(r_center[1]),
        float(r_center[2]),
        float(xWP),
        float(yWP),
    ]
    assert_allclose(got, goldens["desired_hydrostatics"][index], rtol=1e-05, atol=1e-5)


@pytest.mark.parametrize("index", range(len(LIST_FILES)))
def test_hydro_constants(index, ref_test_data, goldens):
    cm, pose = compile_from_yaml(ref_test_data, LIST_FILES[index])
    out = M.member_hydro_constants(cm.topo, cm.geom, pose, rho=1025, g=9.81)
    # atol 1e-6 (reference uses 1e-7): matrix entries reach 1e8, and the
    # batched node summation differs from the reference's sequential
    # accumulation only in float rounding order (~1e-7 absolute residue on
    # exact-zero entries)
    assert_allclose(np.asarray(out["A_hydro"]), goldens["desired_Ahydro"][index], rtol=1e-05, atol=1e-6)
    assert_allclose(np.asarray(out["I_hydro"]), goldens["desired_Ihydro"][index], rtol=1e-05, atol=1e-6)


def test_member_jit_and_grad(ref_test_data):
    """The member physics must be jittable and differentiable w.r.t.
    geometry (the design-sweep requirement the reference can't satisfy)."""
    import jax
    import jax.numpy as jnp

    cm, _ = compile_from_yaml(ref_test_data, LIST_FILES[0])

    @jax.jit
    def submerged_volume(d_scale):
        geom = dataclass_replace_d(cm.geom, cm.geom.d * d_scale)
        pose = M.member_pose(cm.topo, geom)
        _, _, V, _, _, _, _, _ = M.member_hydrostatics(cm.topo, geom, pose)
        return V

    def dataclass_replace_d(geom, new_d):
        import dataclasses

        return dataclasses.replace(geom, d=new_d)

    V1 = submerged_volume(1.0)
    V2 = submerged_volume(1.1)
    assert float(V2) > float(V1)
    g = jax.grad(submerged_volume)(1.0)
    # dV/dscale = 2 V / scale for a cylinder (V ∝ d²)
    assert_allclose(float(g), 2 * float(V1), rtol=1e-6)


def test_end_position_gradient():
    """End-coordinate perturbations must propagate (stations are stored as
    fractions of the traced member length) and stay NaN-free for vertical
    members."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    mi = dict(name="c", type=2, rA=[0, 0, -20], rB=[0, 0, -5], shape="circ",
              stations=[0, 1], d=6.0, t=0.05, dlsMax=1.0)
    cm = M.compile_member(mi)

    def vol(dz):
        g = dataclasses.replace(cm.geom, rB0=cm.geom.rB0 + jnp.array([0.0, 0.0, 1.0]) * dz)
        p = M.member_pose(cm.topo, g)
        return M.member_hydrostatics(cm.topo, g, p)[2]

    g = jax.grad(vol)(0.0)
    assert_allclose(float(g), np.pi / 4 * 36, rtol=1e-8)  # A_cross of d=6 cylinder


def test_rect_submerged_taper_no_nan():
    """Rect members with tapered fully-submerged segments must not leak NaN
    through the masked waterplane-crossing branch."""
    mi = dict(name="r", type=2, rA=[0, 0, -12], rB=[20, 0, -10], shape="rect",
              stations=[0, 1], d=[[5, 10], [10, 10]], t=0.05, dlsMax=1.0)
    cm = M.compile_member(mi)
    pose = M.member_pose(cm.topo, cm.geom)
    Fv, Cm2, V, *_ = M.member_hydrostatics(cm.topo, cm.geom, pose)
    assert np.all(np.isfinite(np.asarray(Fv))) and np.isfinite(float(V))
