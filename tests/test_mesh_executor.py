"""Mesh-native production sweep (the (design, case) mesh executor).

The mesh's contract mirrors the executor's: topology changes
SCHEDULING, never results.  A sweep over the 8-virtual-device CPU mesh
(conftest forces ``--xla_force_host_platform_device_count=8``) must be
bit-identical to the single-device run — same dtypes, same health and
status arrays — at pipeline depth 1 and 3 and through a fault-injected
chunk, with zero extra XLA compiles once the executables are warm.
The guarantee rests on the per-shard design extent equalling the
single-device chunk extent (every shard compiles the exact local
shapes of the 1x1 mesh), so these tests pin that tiling through the
ledger's ``plan`` event as well.
"""

import threading

import numpy as np
import pytest

import jax

from raft_tpu import config as _config
from raft_tpu import sweep as sweep_mod
from raft_tpu.designs import demo_spar
from raft_tpu.obs import ledger as obs_ledger
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.robust import STATUS_OK, STATUS_QUARANTINED
from raft_tpu.sweep import _design_case_mesh, sweep

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5],
          [9.0, 9.0, 6.5, 6.5], [9.6, 9.6, 6.5, 6.5],
          [10.2, 10.2, 6.5, 6.5], [10.8, 10.8, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]

RESULT_KEYS = ("motion_std", "AxRNA_std", "mass", "displacement", "GMT",
               "status")


def _sweep(**kw):
    kw.setdefault("n_iter", 8)
    kw.setdefault("chunk_size", 2)
    return sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES, **kw)


def _assert_bit_identical(a, b):
    """Every result array — metrics, mass properties, health leaves,
    status — must match bit-for-bit INCLUDING dtype."""
    for k in RESULT_KEYS:
        x, y = np.asarray(a[k]), np.asarray(b[k])
        assert x.dtype == y.dtype, (k, x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y, err_msg=k)
    for k in a["health"]:
        x, y = np.asarray(a["health"][k]), np.asarray(b["health"][k])
        assert x.dtype == y.dtype, (f"health.{k}", x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y, err_msg=f"health.{k}")


# ---------------------------------------------------------------------------
# mesh selection (config + factorization)
# ---------------------------------------------------------------------------


def test_mesh_spec_parsing(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_MESH", raising=False)
    assert _config.mesh_spec() is None
    monkeypatch.setenv("RAFT_TPU_MESH", "all")
    assert _config.mesh_spec() == ("all",)
    monkeypatch.setenv("RAFT_TPU_MESH", "auto")
    assert _config.mesh_spec() == ("all",)
    monkeypatch.setenv("RAFT_TPU_MESH", "4")
    assert _config.mesh_spec() == ("count", 4)
    monkeypatch.setenv("RAFT_TPU_MESH", "4x2")
    assert _config.mesh_spec() == ("shape", 4, 2)
    monkeypatch.setenv("RAFT_TPU_MESH", "bogus")
    with pytest.raises(ValueError, match="RAFT_TPU_MESH"):
        _config.mesh_spec()


def test_resolve_mesh_devices(monkeypatch):
    devs = jax.devices()
    assert len(devs) >= 8  # conftest virtual mesh

    # no env, no request: single-device degenerate mesh
    monkeypatch.delenv("RAFT_TPU_MESH", raising=False)
    got, shape = _config.resolve_mesh_devices(None, None)
    assert got == [devs[0]] and shape is None

    # an explicit device list always wins over the env
    monkeypatch.setenv("RAFT_TPU_MESH", "all")
    got, shape = _config.resolve_mesh_devices(devs[:2], None)
    assert got == list(devs[:2]) and shape is None
    with pytest.raises(ValueError, match="empty"):
        _config.resolve_mesh_devices([], None)

    got, shape = _config.resolve_mesh_devices(None, None)
    assert got == list(devs) and shape is None

    monkeypatch.setenv("RAFT_TPU_MESH", "4")
    got, shape = _config.resolve_mesh_devices(None, None)
    assert got == list(devs[:4]) and shape is None

    monkeypatch.setenv("RAFT_TPU_MESH", "4x2")
    got, shape = _config.resolve_mesh_devices(None, None)
    assert got == list(devs[:8]) and shape == (4, 2)

    monkeypatch.setenv("RAFT_TPU_MESH", str(len(devs) + 1))
    with pytest.raises(ValueError, match="device"):
        _config.resolve_mesh_devices(None, None)


def test_design_case_mesh_factorization():
    devs = jax.devices()[:8]
    # default: every device on the design axis (the bit-identity choice)
    mesh = _design_case_mesh(devs, n_cases=2)
    assert mesh.devices.shape == (8, 1)
    assert mesh.axis_names == ("design", "case")
    # one device is the degenerate 1x1 mesh of the same code path
    assert _design_case_mesh(devs[:1], n_cases=7).devices.shape == (1, 1)
    # an explicit shape pins the factorization
    assert _design_case_mesh(devs, 2, shape=(4, 2)).devices.shape == (4, 2)
    with pytest.raises(ValueError, match="does not use"):
        _design_case_mesh(devs, 2, shape=(4, 1))
    with pytest.raises(ValueError, match="does not divide"):
        _design_case_mesh(devs, 3, shape=(4, 2))


# ---------------------------------------------------------------------------
# bit-identity + zero recompiles (the tentpole contract)
# ---------------------------------------------------------------------------


@pytest.mark.sentinel
def test_mesh_bit_identical_no_recompile(monkeypatch):
    """Single-device vs the full 8-device design mesh, at pipeline depth
    1 and 3 and through a fault-injected chunk: bit-identical results
    (all dtypes, health + status arrays) and ZERO new XLA compiles once
    both topologies are warm."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    devs = jax.devices()
    # chunk_size=1 on 8 designs fills all 8 shards (global chunk 8)
    base = _sweep(chunk_size=1, device=devs[0])   # warm the 1x1 mesh
    meshed = _sweep(chunk_size=1, devices=devs)   # warm the 8x1 mesh
    assert (base["status"] == STATUS_OK).all()
    _assert_bit_identical(base, meshed)

    with RecompileSentinel() as s:
        snap = s.snapshot()

        repeat = _sweep(chunk_size=1, devices=devs)
        s.assert_no_recompile(snap, "warm mesh sweep")
        _assert_bit_identical(base, repeat)

        monkeypatch.setenv("RAFT_TPU_PIPELINE", "1")
        depth1 = _sweep(chunk_size=1, devices=devs)
        s.assert_no_recompile(snap, "depth-1 mesh sweep")
        _assert_bit_identical(base, depth1)

        monkeypatch.setenv("RAFT_TPU_PIPELINE", "3")
        depth3 = _sweep(chunk_size=1, devices=devs)
        s.assert_no_recompile(snap, "depth-3 mesh sweep")
        _assert_bit_identical(base, depth3)
        monkeypatch.delenv("RAFT_TPU_PIPELINE")

        # a persistently faulting design: retry, then bisection down the
        # shard tiling — the re-runs ride the SAME chunk executables
        poison = 5

        def hook(idx, dispatch):
            if (np.asarray(idx) == poison).any():
                raise RuntimeError("injected chunk fault")
            return dispatch(idx)

        monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
        with pytest.warns(RuntimeWarning, match="isolating faults"):
            faulted = _sweep(chunk_size=1, devices=devs)
        s.assert_no_recompile(snap, "fault-isolating mesh sweep")
        monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)

    assert faulted["status"][poison] == STATUS_QUARANTINED
    ok = faulted["status"] == STATUS_OK
    assert ok.tolist() == [i != poison for i in range(8)]
    # healthy rows recovered by bisection are bit-identical too (the
    # align= snapping keeps every design at its original local row)
    np.testing.assert_array_equal(faulted["motion_std"][ok],
                                  base["motion_std"][ok])
    assert np.isnan(faulted["motion_std"][poison]).all()


def test_mesh_auto_sizes_design_axis_to_workload():
    """Shards past ceil(n_designs / chunk) would hold only padding; the
    sweep drops them instead (8 designs / chunk 4 -> 2 of 8 devices),
    and the result is still bit-identical to single-device."""
    devs = jax.devices()
    base = _sweep(chunk_size=4, device=devs[0])
    meshed = _sweep(chunk_size=4, devices=devs)
    _assert_bit_identical(base, meshed)


def test_mesh_explicit_case_axis_shape(monkeypatch):
    """RAFT_TPU_MESH=DxC pins the factorization.  A case extent > 1
    shrinks each shard's local sea-state batch, so this path promises
    fp-tolerance agreement (status exactly), not bitwise."""
    devs = jax.devices()
    base = _sweep(chunk_size=2, device=devs[0])
    monkeypatch.setenv("RAFT_TPU_MESH", "4x2")
    meshed = _sweep(chunk_size=2)
    np.testing.assert_array_equal(base["status"], meshed["status"])
    np.testing.assert_allclose(meshed["motion_std"], base["motion_std"],
                               rtol=1e-12, atol=0)
    np.testing.assert_allclose(meshed["AxRNA_std"], base["AxRNA_std"],
                               rtol=1e-12, atol=0)


def test_mesh_program_collective_set_is_golden():
    """The collective-op set of the 8-shard chunk executables is a
    CONTRACT, not an accident: the (design, case) mesh path is
    shard-local by construction, so the partA/partB programs and the
    chunk-gather selector compiled for the full 8-device mesh must
    contain NO collectives — and graftaudit.toml must pin exactly that
    (empty expected sets), so any resharding-inserted all-gather fails
    CI the moment it appears."""
    from raft_tpu.analysis import graftaudit

    devs = jax.devices()
    # one sea state: a jit_key no other test compiles, so the compile
    # hook (cold-memo only) is guaranteed to fire for A and B here
    with graftaudit.collecting():
        graftaudit.take_results()
        sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES[:1],
              n_iter=8, chunk_size=1, devices=devs)
        results = graftaudit.take_results()

    by = {r.program: r for r in results}
    assert {"A@8", "B@8", "gather@8"} <= set(by), sorted(by)
    for prog in ("A@8", "B@8", "gather@8"):
        assert by[prog].collectives == {}, (prog, by[prog].collectives)
        assert not [f for f in by[prog].findings
                    if f.rule == "GA-COLLECTIVE"], prog

    # the checked-in expected set pins the same contract for CI
    spec = graftaudit.load_spec(graftaudit.find_config_path())
    for prog in ("A@8", "B@8", "gather@8"):
        assert spec.expect_collectives.get(prog) == [], prog


# ---------------------------------------------------------------------------
# ledger: plan tiling, per-device dispatch, fault/dispatch overlap
# ---------------------------------------------------------------------------


def _ledger_sweep(tmp_path, monkeypatch, name, **kw):
    ldir = tmp_path / name
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    out = _sweep(**kw)
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    runs = obs_ledger.list_runs(str(ldir))
    assert len(runs) == 1, runs
    return out, obs_ledger.read_events(runs[0])


def test_mesh_ledger_plan_and_dispatch(tmp_path, monkeypatch):
    devs = jax.devices()
    _sweep(chunk_size=2, devices=devs)  # warm
    out, events = _ledger_sweep(tmp_path, monkeypatch, "mesh",
                                chunk_size=2, devices=devs)
    assert (out["status"] == STATUS_OK).all()
    by = {}
    for ev in events:
        by.setdefault(ev["event"], []).append(ev)

    plan = by["plan"][0]
    # 8 designs / chunk 2 -> 4 useful shards; the global chunk is 4
    # single-device-shaped chunks (the per-shard extent stays 2)
    assert plan["mesh"] == [4, 1]
    assert plan["chunk_size"] == 8 and plan["n_chunks"] == 1
    assert len(plan["devices"]) == 4

    disp = by["chunk_dispatch"][0]
    assert disp["devices"] == plan["devices"]
    fetch = by["chunk_fetch"][0]
    # per-shard d2h split: one entry per device, bytes on each
    per_device = fetch.get("per_device")
    assert per_device and len(per_device) == 4
    assert all(b > 0 for b in per_device.values())


def test_mesh_fault_does_not_stall_other_shards(tmp_path, monkeypatch):
    """Overlap proof: while one global chunk's fault is being isolated
    on the worker, the main loop keeps dispatching the next chunk.  The
    hook makes it deterministic — the isolation re-run cannot raise (so
    the quarantine cannot land) until chunk 1 has been dispatched."""
    devs = jax.devices()
    monkeypatch.setenv("RAFT_TPU_PIPELINE", "1")
    _sweep(chunk_size=1, devices=devs[:4])  # warm (4x1 mesh, 2 chunks)

    seen_chunk1 = threading.Event()
    first_call = {"live": True}

    def hook(idx, dispatch):
        idx = np.asarray(idx)
        if idx[0] == 4:  # second global chunk reached the executor
            seen_chunk1.set()
        if (idx == 0).any():
            if first_call["live"]:
                first_call["live"] = False  # dispatch-time fault, main loop
            else:
                # isolation re-run (worker thread): hold the fault until
                # the main loop has provably moved on to chunk 1
                assert seen_chunk1.wait(30.0)
            raise RuntimeError("injected chunk fault")
        return dispatch(idx)

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    ldir = tmp_path / "overlap"
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    with pytest.warns(RuntimeWarning, match="isolating faults"):
        out = _sweep(chunk_size=1, devices=devs[:4])
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)

    assert out["status"][0] == STATUS_QUARANTINED
    assert (out["status"][1:] == STATUS_OK).all()

    events = obs_ledger.read_events(obs_ledger.list_runs(str(ldir))[0])
    names = [ev["event"] for ev in events]
    i_fault = names.index("chunk_fault")
    i_disp1 = next(i for i, ev in enumerate(events)
                   if ev["event"] == "chunk_dispatch" and ev["chunk"] == 1)
    i_quar = names.index("design_quarantined")
    # the ledger timeline proves the overlap: fault recorded, NEXT chunk
    # dispatched, and only then the quarantine from the worker
    assert i_fault < i_disp1 < i_quar


# ---------------------------------------------------------------------------
# per-device live metrics
# ---------------------------------------------------------------------------


@pytest.fixture
def metrics_env(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_LEDGER", raising=False)
    monkeypatch.setenv("RAFT_TPU_METRICS", "1")
    obs_metrics.reset()
    yield obs_metrics
    from raft_tpu.obs import live as obs_live

    obs_live.stop_server()
    obs_metrics.reset()


def test_per_device_metrics_labels(metrics_env):
    """The same event vocabulary a mesh run emits must label transfer
    bytes and memory gauges per device and expose per-device in-flight
    depth through /status."""
    obs_metrics.observe_event("run_start", {
        "t": 1.0, "run_id": "m1", "kind": "sweep",
        "fingerprint": {"n_designs": 8, "n_cases": 2}})
    obs_metrics.observe_event("chunk_dispatch", {
        "chunk": 0, "in_flight": 2, "devices": [0, 1, 2, 3]})
    obs_metrics.observe_event("chunk_fetch", {
        "chunk": 0, "bytes": 40, "per_device": {"0": 10, "1": 30}})
    obs_metrics.observe_event("transfer", {
        "what": "resident_batch", "direction": "h2d", "bytes": 64,
        "per_device": {"0": 32, "1": 32}})
    obs_metrics.observe_event("transfer", {
        "what": "design_params", "direction": "h2d", "bytes": 8})
    obs_metrics.observe_event("device_memory", {
        "device": "cpu:1", "bytes_in_use": 123, "peak_bytes": 456})

    m = obs_metrics.std()
    assert m.transfer_bytes.value(direction="d2h", device="0") == 10
    assert m.transfer_bytes.value(direction="d2h", device="1") == 30
    assert m.transfer_bytes.value(direction="h2d", device="0") == 32
    # events with no split stay on the aggregate label
    assert m.transfer_bytes.value(direction="h2d", device="all") == 8
    assert m.device_bytes_in_use.value(device="cpu:1") == 123
    assert m.device_peak_bytes.value(device="cpu:1") == 456

    st = obs_metrics.status_snapshot()["active"]
    assert st["per_device_in_flight"] == {
        "0": 2, "1": 2, "2": 2, "3": 2}


def test_shard_bytes_per_device_split():
    """obs_ledger.shard_bytes splits a sharded pytree's footprint by
    device id (the source of every per_device event field)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4, 1), ("design", "case"))
    x = jax.device_put(np.zeros((8, 3), dtype=np.float64),
                       NamedSharding(mesh, P("design")))
    split = obs_ledger.shard_bytes([x])
    assert set(split) == {str(d.id) for d in devs}
    assert all(b == 2 * 3 * 8 for b in split.values())  # 2 rows x 3 f64


# ---------------------------------------------------------------------------
# checkpointing across topologies
# ---------------------------------------------------------------------------


def test_mesh_checkpoint_records_topology_and_resumes_anywhere(
        tmp_path, monkeypatch):
    """A mesh sweep's checkpoint records the mesh shape (post-mortem
    attribution) but resume is topology-independent: a single-device
    resume of an 8-device sweep picks up where the checkpoint left off,
    bit-identically."""
    devs = jax.devices()
    ckpt = str(tmp_path / "mesh.ckpt")
    base = _sweep(chunk_size=2, device=devs[0])

    # fault chunk 1 at dispatch so the mesh sweep quarantines design 5;
    # its checkpoint then has real per-design state to resume from
    poison = 5

    def hook(idx, dispatch):
        if (np.asarray(idx) == poison).any():
            raise RuntimeError("injected chunk fault")
        return dispatch(idx)

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    with pytest.warns(RuntimeWarning, match="isolating faults"):
        meshed = _sweep(chunk_size=2, devices=devs, checkpoint=ckpt)
    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)
    assert meshed["status"][poison] == STATUS_QUARANTINED

    with np.load(ckpt) as snap:
        assert snap["mesh_shape"].tolist() == [4, 1]
        assert bool(snap["done"].all())

    # resume on ONE device: every design is done, nothing recomputes,
    # and the quarantined row survives the topology change
    resumed = _sweep(chunk_size=2, device=devs[0], checkpoint=ckpt)
    assert resumed["status"][poison] == STATUS_QUARANTINED
    ok = resumed["status"] == STATUS_OK
    np.testing.assert_array_equal(resumed["motion_std"][ok],
                                  base["motion_std"][ok])
