"""MHK (underwater rotor) path + io_utils tests."""

import numpy as np
import pytest
import yaml

import raft_tpu
from raft_tpu import io_utils

DESIGNS = "/root/reference/designs"


@pytest.fixture(scope="module")
def rm1_model():
    with open(f"{DESIGNS}/RM1_Floating.yaml") as f:
        design = yaml.load(f, Loader=yaml.FullLoader)
    return raft_tpu.Model(design)


def test_rm1_underwater_rotor(rm1_model):
    fowt = rm1_model.fowtList[0]
    rot = fowt.rotorList[0]
    assert rot.r3[2] < 0  # submerged hub

    # reference quirk (kept): the blade-member submergence mask runs on
    # hub-RELATIVE z, so RM1's horizontal azimuths [0, 180] contribute
    # exactly nothing (raft_member.py:910 with relative rA0/rB0)
    A_rot, I_rot = rot.calcHydroConstants(rho=fowt.rho_water)
    assert np.all(np.isfinite(A_rot))
    assert A_rot[0, 0] == 0.0

    # with blades pointing down/up, the lower blade counts
    rot.azimuths = [90.0, 270.0]
    rot.bladeMemberList = []
    A_v, I_v = rot.calcHydroConstants(rho=fowt.rho_water)
    assert A_v[0, 0] > 0
    assert np.all(np.isfinite(I_v))
    rot.azimuths = [0.0, 180.0]
    rot.bladeMemberList = []


def test_rm1_case_with_cavitation(rm1_model):
    design = rm1_model.design
    case = dict(zip(design["cases"]["keys"], design["cases"]["data"][0]))
    case["iCase"] = 0
    rm1_model.solveStatics(case)
    rm1_model.solveDynamics(case)
    fowt = rm1_model.fowtList[0]
    res = {}
    fowt.saveTurbineOutputs(res, case)
    assert "cavitation" in res
    cav = np.asarray(res["cavitation"])
    assert cav.shape[0] == fowt.rotorList[0].nBlades
    assert np.all(np.isfinite(cav))
    # RM1 at design flow speed should not cavitate
    assert np.all(cav > 0)


def test_io_utils_roundtrip(tmp_path):
    # clean_raft_dict makes numpy-laden dicts YAML-safe
    d = {"a": np.float64(1.5), "b": [np.int64(2), np.array([1.0, 2.0])],
         "c": {"d": np.array([3])}}
    clean = io_utils.clean_raft_dict(d)
    text = yaml.safe_dump(clean)
    assert yaml.safe_load(text) == {"a": 1.5, "b": [2, [1.0, 2.0]], "c": {"d": [3]}}

    # unique case headings
    heads, step, n = io_utils.get_unique_case_headings(
        ["wave_heading", "wave_heading2"], [[0, 30], [30, 60], [0, 60]])
    assert heads == [0.0, 30.0, 60.0] and step == 30.0 and n == 3

    # parametric case builder appends rows on the chosen column
    design = {"cases": {"keys": ["wind_speed", "x"], "data": [[8.0, 0]]}}
    io_utils.parametric_case_builder(design, "wind_speed", 6.0, 2.0, 2)
    assert [r[0] for r in design["cases"]["data"]] == [6.0, 8.0, 10.0]
