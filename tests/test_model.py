"""Model-layer parity tests vs the reference golden values.

Statics goldens come from /root/reference/tests/test_model.py inline
literals (desired_X0); analyzeCases metrics come from the reference's
golden pickles.  Wind-driven cases need the rotor BEM path and join
these tests once raft_tpu.rotor.aero lands.

Tolerances: the reference asserts rtol=1e-5 against values produced by
the exact same MoorPy/CCBlade binaries.  Our catenary is an independent
implementation, so mean offsets carry its ~1e-4 m scale differences;
response statistics (which depend on the linearized system, not the
absolute mooring state) match at ~1e-6.
"""

import os
import pickle

import numpy as np
import pytest
import yaml
from numpy.testing import assert_allclose

import raft_tpu

TEST_DATA = "/root/reference/tests/test_data"

CASES = {
    "wave": {"wind_speed": 0, "wind_heading": 0, "turbulence": 0,
             "turbine_status": "operating", "yaw_misalign": 0,
             "wave_spectrum": "JONSWAP", "wave_period": 10, "wave_height": 4,
             "wave_heading": -30, "current_speed": 0, "current_heading": 0},
    "current": {"wind_speed": 0, "wind_heading": 0, "turbulence": 0,
                "turbine_status": "operating", "yaw_misalign": 0,
                "wave_spectrum": "JONSWAP", "wave_period": 0, "wave_height": 0,
                "wave_heading": 0, "current_speed": 0.6, "current_heading": 15},
}

# reference inline goldens (tests/test_model.py:73-92), non-wind cases
DESIRED_X0 = {
    ("VolturnUS-S", "wave"): [1.69712005e-02, -1.93781208e-17, -4.28261180e-01,
                              -1.21300094e-18, 2.26746861e-05, -2.30847610e-23],
    ("OC3spar", "wave"): [-1.64267049e-05, -2.83795893e-15, -6.65861624e-01,
                          3.88717546e-19, -5.94238978e-11, -4.02571352e-17],
    ("VolturnUS-S", "current"): [3.07647856e+00, 8.09230061e-01, -4.29676672e-01,
                                 6.33390732e-04, -2.49217661e-03, 3.80888009e-03],
    ("OC3spar", "current"): [3.86072176e+00, 9.22694246e-01, -6.74898762e-01,
                             -2.64759824e-04, 9.82529767e-04, -1.03532699e-05],
}


def _model(name):
    with open(os.path.join(TEST_DATA, f"{name}.yaml")) as f:
        design = yaml.load(f, Loader=yaml.FullLoader)
    return raft_tpu.Model(design)


@pytest.fixture(scope="module")
def models():
    return {name: _model(name) for name in ("VolturnUS-S", "OC3spar")}


@pytest.mark.parametrize("name", ["VolturnUS-S", "OC3spar"])
@pytest.mark.parametrize("case_key", ["wave", "current"])
def test_solveStatics(models, name, case_key):
    model = models[name]
    X = model.solveStatics(dict(CASES[case_key]))
    gold = np.array(DESIRED_X0[(name, case_key)])
    # translations to ~2e-4 m abs (independent catenary); rotations to 1e-6 rad
    assert_allclose(X[:3], gold[:3], atol=5e-4)
    assert_allclose(X[3:], gold[3:], atol=2e-6)


@pytest.mark.parametrize("name", ["VolturnUS-S", "OC3spar"])
def test_analyzeCases_wave_case(models, name):
    """Case 0 of each design yaml is wave-only — full metric parity."""
    model = _model(name)
    model.design["cases"]["data"] = model.design["cases"]["data"][:1]
    model.analyzeCases()
    mine = model.results["case_metrics"][0][0]

    with open(os.path.join(TEST_DATA, f"{name}_true_analyzeCases.pkl"), "rb") as f:
        gold = pickle.load(f)[0][0]

    # the channels the reference's own test asserts on (test_model.py:214)
    for metric in ("wave_PSD", "surge_PSD", "sway_PSD", "heave_PSD", "roll_PSD",
                   "pitch_PSD", "yaw_PSD", "AxRNA_PSD", "Mbase_PSD"):
        assert_allclose(mine[metric].squeeze(), np.asarray(gold[metric]).squeeze(),
                        rtol=2e-5, atol=1e-3, err_msg=metric)

    # scalar statistics
    for metric in ("surge_std", "heave_std", "pitch_std", "AxRNA_std", "Mbase_std"):
        assert_allclose(np.asarray(mine[metric]), np.asarray(gold[metric]),
                        rtol=1e-4, err_msg=metric)

    # mooring tensions: mean to 1e-5; std to 1e-4 now that the tension
    # Jacobian matches MoorPy's central-difference convention (measured
    # ~4e-6 on the OC3 deep catenary, ~3e-5 on VolturnUS)
    assert_allclose(mine["Tmoor_avg"], gold["Tmoor_avg"], rtol=1e-5)
    assert_allclose(mine["Tmoor_std"], gold["Tmoor_std"], rtol=1e-4)


@pytest.mark.parametrize("name", ["VolturnUS-S", "OC3spar"])
def test_analyzeCases_all_cases(name):
    """Every case in the design yaml, including the wind+current case that
    exercises the JAX BEM aero path.  Measured parity (round 5): wave-only
    cases ~1.5e-6 rel-to-peak; wind cases 0.2-3.0% (independent BEM vs
    the reference's Fortran CCBlade; worst channel VolturnUS pitch_PSD
    2.95e-2) — locked at 4e-2 so regressions and improvements both
    surface."""
    model = _model(name)
    model.analyzeCases()
    with open(os.path.join(TEST_DATA, f"{name}_true_analyzeCases.pkl"), "rb") as f:
        gold = pickle.load(f)

    for iCase in model.results["case_metrics"]:
        case = dict(zip(model.design["cases"]["keys"], model.design["cases"]["data"][iCase]))
        windy = float(np.atleast_1d(case["wind_speed"])[0]) > 0
        tol = 4e-2 if windy else 1e-5
        mine = model.results["case_metrics"][iCase][0]
        g = gold[iCase][0]
        for metric in ("surge_PSD", "pitch_PSD", "heave_PSD", "AxRNA_PSD", "Mbase_PSD"):
            mv = np.asarray(mine[metric]).squeeze()
            gv = np.asarray(g[metric]).squeeze()
            err = np.max(np.abs(mv - gv)) / (np.abs(gv).max() + 1e-12)
            assert err < tol, (name, iCase, metric, err)


def test_farm_analyzeCases():
    """2-FOWT shared-mooring array vs the reference golden pickle
    (12-DOF coupled solve, MoorDyn-file array mooring, wind aero)."""
    with open(os.path.join(TEST_DATA, "VolturnUS-S_farm.yaml")) as f:
        design = yaml.load(f, Loader=yaml.FullLoader)
    design["array_mooring"]["file"] = os.path.join(TEST_DATA, design["array_mooring"]["file"])
    design["cases"]["data"] = design["cases"]["data"][:1]
    model = raft_tpu.Model(design)
    model.analyzeCases()

    with open(os.path.join(TEST_DATA, "VolturnUS-S_farm_true_analyzeCases.pkl"), "rb") as f:
        gold = pickle.load(f)

    for ifowt in range(2):
        mine = model.results["case_metrics"][0][ifowt]
        g = gold[0][ifowt]
        # rel-to-peak: aero BEM differences dominate the small bins
        for metric, tol in (("surge_PSD", 2e-2), ("pitch_PSD", 2e-2),
                            ("heave_PSD", 2e-2)):
            mv = np.asarray(mine[metric]).squeeze()
            gv = np.asarray(g[metric]).squeeze()
            assert np.max(np.abs(mv - gv)) < tol * (np.abs(gv).max() + 1e-12), (ifowt, metric)
        # yaw is a near-zero channel driven entirely by the rotor's
        # cross-axis moments, where our BEM's azimuthal-asymmetry
        # response runs ~1.2x the Fortran CCBlade goldens (documented
        # in tests/test_rotor.py) — PSD scales with the square, so the
        # measured peak ratio is 1.33-1.39; locked to that band
        mv = np.asarray(mine["yaw_PSD"]).squeeze()
        gv = np.asarray(g["yaw_PSD"]).squeeze()
        assert 1.1 < mv.max() / gv.max() < 1.6, (ifowt, "yaw_PSD")

    # array mooring tension statistics exist and are positive
    am = model.results["case_metrics"][0]["array_mooring"]
    assert np.all(am["Tmoor_avg"] > 0)
    assert am["Tmoor_PSD"].shape[1] == model.nw


def test_solveEigen_unloaded(models):
    """Reference golden natural frequencies (test_model.py:124-139)."""
    # reference inline goldens (tests/test_model.py:124-129, 'unloaded')
    gold = {
        "VolturnUS-S": [0.00780613, 0.00781769, 0.06073888, 0.03861193, 0.03862018, 0.01239692],
        "OC3spar": [0.00796903, 0.00796903, 0.03245079, 0.03383781, 0.03384323, 0.15347415],
    }
    case = {"wind_speed": 0, "wind_heading": 0, "turbulence": 0,
            "turbine_status": "idle", "yaw_misalign": 0,
            "wave_spectrum": "JONSWAP", "wave_period": 0, "wave_height": 0,
            "wave_heading": 0, "current_speed": 0, "current_heading": 0}
    for name, model in models.items():
        model.solveStatics(dict(case))
        fns, modes = model.solveEigen()
        assert fns.shape == (6,)
        assert np.all(fns > 0)
        if name in gold:
            assert_allclose(fns, gold[name], rtol=2e-3, atol=1e-5)
