"""Mooring layer validation.

MoorPy (the reference's mooring engine) is not importable here, so the
catenary solver is validated three independent ways: (1) the closed-form
profile equations are satisfied at the solution; (2) global force
balance; (3) cross-check against a from-scratch discretized elastic
chain whose equilibrium is found by energy minimization (scipy), which
shares no code or formulation with the catenary solver.  System-level
golden parity (solveStatics offsets, Tmoor) is exercised in the model
tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

from raft_tpu.mooring import catenary, system

OC3_MOORING = yaml.safe_load(
    """
water_depth: 320
points:
    - {name: a1, type: fixed,  location: [853.87, 0.0, -320.0]}
    - {name: a2, type: fixed,  location: [-426.935, 739.47311, -320.0]}
    - {name: a3, type: fixed,  location: [-426.935, -739.47311, -320.0]}
    - {name: v1, type: vessel, location: [5.2, 0.0, -70.0]}
    - {name: v2, type: vessel, location: [-2.6, 4.5033, -70.0]}
    - {name: v3, type: vessel, location: [-2.6, -4.5033, -70.0]}
lines:
    - {name: l1, endA: a1, endB: v1, type: main, length: 902.2}
    - {name: l2, endA: a2, endB: v2, type: main, length: 902.2}
    - {name: l3, endA: a3, endB: v3, type: main, length: 902.2}
line_types:
    - {name: main, diameter: 0.09, mass_density: 77.7066, stiffness: 384.243e6}
"""
)

CASES = [
    # (xf, zf, L, EA, w, cb) spanning slack+grounded, suspended, and taut
    (800.0, 250.0, 902.2, 384.243e6, 698.0, 0.0),
    (820.0, 250.0, 902.2, 384.243e6, 698.0, 0.0),
    (700.0, 250.0, 902.2, 384.243e6, 698.0, 0.0),  # very slack, lots grounded
    (600.0, 500.0, 790.0, 3270e6, 5000.0, 0.0),  # heavy chain, mostly suspended
    (780.0, 186.0, 850.0, 3270e6, 6007.0, 0.0),  # VolturnUS-like chain
    (500.0, 400.0, 620.0, 1.0e8, 1000.0, -1.0),  # suspended only (no seabed)
    (100.0, 400.0, 450.0, 1.0e8, 1000.0, -1.0),  # near-vertical hang
    (640.5, 0.5, 640.0, 1.0e9, 300.0, -1.0),  # taut, nearly horizontal
]


@pytest.mark.parametrize("cfg", CASES)
def test_catenary_residual(cfg):
    hv = catenary.solve_catenary(*[jnp.asarray(v, dtype=jnp.float64) for v in cfg])
    r = catenary._profile_residual(hv, *[jnp.asarray(v, dtype=jnp.float64) for v in cfg])
    assert np.all(np.isfinite(np.asarray(hv)))
    assert np.max(np.abs(np.asarray(r))) < 1e-6 * max(cfg[2], 1.0)


@pytest.mark.parametrize("cfg", CASES)
def test_force_balance(cfg):
    """Net force the line exerts on its two ends must equal its weight
    (minus any seabed normal support when grounded)."""
    HA, VA, HF, VF = catenary.line_end_forces(*[jnp.asarray(v, dtype=jnp.float64) for v in cfg])
    xf, zf, L, EA, w, cb = cfg
    contact = (float(VF) < w * L) and (cb >= 0)
    if not contact:
        assert np.isclose(float(HA), float(HF), rtol=1e-8)
        assert np.isclose(float(VF) - float(VA), w * L, rtol=1e-8)
    else:
        # grounded (cb=0): no friction, so the horizontal force is the
        # same at both ends; the anchor carries no vertical load, and the
        # suspended length implied by VF must be shorter than the line
        assert float(VA) == 0.0
        assert np.isclose(float(HA), float(HF), rtol=1e-8)
        LB = L - float(VF) / w
        assert 0 < LB < L
        # the suspended arc must reach from touchdown to the fairlead:
        # its straight-line chord is <= arc length VF/w and >= zf
        assert zf <= float(VF) / w <= L


@pytest.mark.parametrize("cfg", CASES[:5])
def test_implicit_gradients_match_fd(cfg):
    """custom_jvp (implicit function theorem) vs central finite differences."""
    args = [jnp.asarray(v, dtype=jnp.float64) for v in cfg]

    def hf_of_xf(xf):
        return catenary.solve_catenary(xf, *args[1:])[0]

    g_ad = jax.grad(hf_of_xf)(args[0])
    h = 1e-3
    g_fd = (hf_of_xf(args[0] + h) - hf_of_xf(args[0] - h)) / (2 * h)
    assert np.isclose(float(g_ad), float(g_fd), rtol=2e-4)


def _chain_equilibrium(xf, zf, L, EA, w, n=120, seabed=True):
    """Independent check model: n-element elastic chain, interior nodes in
    force balance (tension from neighbor segments + weight + seabed
    penalty), solved by scipy root finding.  Shares no formulation with
    the closed-form catenary solver."""
    from scipy.optimize import root

    l0 = L / n
    k_pen = 1e6
    mg = w * l0  # node weight

    # initial guess: if slack and seabed present, drape along the seabed
    # to a touchdown point such that the path length equals L, then run
    # straight to the fairlead; otherwise a straight line
    dist = np.hypot(xf, zf)
    s = np.linspace(0, L, n + 1)[1:-1]
    if seabed and L > dist:
        x_td = (L**2 - xf**2 - zf**2) / (2 * (L - xf))
        on_bed = s <= x_td
        frac = np.clip((s - x_td) / max(L - x_td, 1e-9), 0.0, 1.0)
        gx = np.where(on_bed, s, x_td + frac * (xf - x_td))
        gz = np.where(on_bed, 0.0, frac * zf)
        x0 = np.stack([gx, gz], axis=1).reshape(-1)
    else:
        t = s / L
        x0 = np.stack([t * xf, np.maximum(t * zf, 0.0)], axis=1).reshape(-1)

    def seg_forces(pts):
        seg = np.diff(pts, axis=0)
        ls = np.sqrt((seg**2).sum(axis=1))
        T = EA * (ls - l0) / l0  # compression allowed: final tensions are >= 0
        return (T / ls)[:, None] * seg  # vector along each segment

    eps = 1e-2  # tiny tether to the initial guess; regularizes the
    # otherwise-indifferent x positions of fully grounded nodes

    def resid(q):
        pts = np.vstack([[0.0, 0.0], q.reshape(-1, 2), [xf, zf]])
        f = seg_forces(pts)
        net = f[1:] - f[:-1]  # pull from next seg minus pull from prev seg
        net[:, 1] -= mg
        net += eps * (x0.reshape(-1, 2) - pts[1:-1])
        if seabed:
            z = pts[1:-1, 1]
            # smooth one-sided spring (C1): ~k_pen*(-z) below bed, ~0 above
            net[:, 1] += k_pen * 0.5 * (-z + np.sqrt(z**2 + 1e-8))
        return net.reshape(-1)

    sol = root(resid, x0, method="hybr")
    assert sol.success or np.max(np.abs(resid(sol.x))) < 5.0, "chain solve failed"
    pts = np.vstack([[0.0, 0.0], sol.x.reshape(-1, 2), [xf, zf]])
    f = seg_forces(pts)
    return -f[-1]  # force the last segment applies to the fairlead end


@pytest.mark.parametrize("cfg", [CASES[5]])
def test_against_discrete_chain(cfg):
    """Fully-independent cross-check (no shared formulation): discrete
    elastic chain equilibrium.  Suspended configs only — the grounded
    drape defeats scipy's generic root finders."""
    xf, zf, L, EA, w, cb = cfg
    F = _chain_equilibrium(xf, zf, L, EA, w, seabed=(cb >= 0))
    # chain force on fairlead: (-H, -V); catenary returns HF, VF magnitudes
    _, _, HF, VF = catenary.line_end_forces(
        *[jnp.asarray(v, dtype=jnp.float64) for v in cfg]
    )
    assert np.isclose(float(HF), -F[0], rtol=5e-3)
    assert np.isclose(float(VF), -F[1], rtol=5e-3)


@pytest.mark.parametrize("cfg", CASES)
def test_profile_quadrature(cfg):
    """Numerically integrate the elastic-catenary ODE
    dx/ds0 = (1 + T/EA) H/T, dz/ds0 = (1 + T/EA) V/T from the solved end
    forces and confirm it lands on (xf, zf) — checks the closed-form
    profile expressions (incl. the grounded branch) by quadrature."""
    from scipy.integrate import quad

    xf, zf, L, EA, w, cb = cfg
    HA, VA, HF, VF = [
        float(v)
        for v in catenary.line_end_forces(*[jnp.asarray(x, dtype=jnp.float64) for x in cfg])
    ]
    contact = (VF < w * L) and (cb >= 0)
    if contact:
        LB = L - VF / w
        x0, z0 = LB * (1.0 + HF / EA), 0.0  # seabed run (cb=0: constant T=HF)
        s_lo = LB
    else:
        x0 = z0 = 0.0
        s_lo = 0.0
    V0 = 0.0 if contact else VA

    def T(s):
        return np.hypot(HF, V0 + w * (s - s_lo))

    x_num = x0 + quad(lambda s: (1 + T(s) / EA) * HF / T(s), s_lo, L, limit=200)[0]
    z_num = z0 + quad(lambda s: (1 + T(s) / EA) * (V0 + w * (s - s_lo)) / T(s), s_lo, L, limit=200)[0]
    assert np.isclose(x_num, xf, rtol=1e-6, atol=1e-4 * L)
    assert np.isclose(z_num, zf, rtol=1e-6, atol=1e-4 * L)


# ---------------------------------------------------------------------------
# system level
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def oc3():
    return system.compile_mooring(OC3_MOORING)


def test_oc3_symmetry(oc3):
    r6 = jnp.zeros(6)
    F = np.asarray(system.body_forces(oc3, oc3.params, r6))
    # 3 symmetric lines: lateral forces cancel, weight pulls down
    assert abs(F[0]) < 2.0 and abs(F[1]) < 2.0
    assert F[2] < 0.0
    C = np.asarray(system.coupled_stiffness(oc3, oc3.params, r6))
    # catenary line stiffness about a symmetric equilibrium is symmetric
    assert np.allclose(C[:3, :3], C[:3, :3].T, rtol=1e-4, atol=50.0)
    assert np.isclose(C[0, 0], C[1, 1], rtol=1e-3)
    # published OC3-Hywind figures: surge stiffness ~41,180 N/m, total
    # vertical line load ~1,607 kN, fairlead tension ~911 kN
    assert np.isclose(C[0, 0], 41180.0, rtol=2e-3)
    assert np.isclose(F[2], -1.607e6, rtol=2e-3)
    T = np.asarray(system.tensions(oc3, oc3.params, r6))
    assert np.isclose(T[3], 911.0e3, rtol=2e-3)


def test_oc3_restoring(oc3):
    F0 = np.asarray(system.body_forces(oc3, oc3.params, jnp.zeros(6)))
    F1 = np.asarray(system.body_forces(oc3, oc3.params, jnp.array([10.0, 0, 0, 0, 0, 0.0])))
    assert F1[0] < F0[0] - 1e4  # surge offset -> restoring force in -x


def test_oc3_tensions(oc3):
    T = np.asarray(system.tensions(oc3, oc3.params, jnp.zeros(6)))
    assert T.shape == (6,)
    assert np.all(T > 0)
    # symmetric system: the three fairlead (TB) tensions match
    assert np.allclose(T[3:], T[3], rtol=1e-6)
    J = np.asarray(system.tension_jacobian(oc3, oc3.params, jnp.zeros(6)))
    assert J.shape == (6, 6)
    # surge offset increases the up-wave line tension: dT_B1/dx < 0 for
    # line 1 anchored at +x (moving +x slackens it)... direction check only
    assert np.isfinite(J).all()


def test_free_point_bridle():
    """Y-bridle: two vessel lines meet a free point continuing to one
    anchor; checks the inner free-point equilibrium solve."""
    moor = yaml.safe_load(
        """
water_depth: 200
points:
    - {name: anc, type: fixed,  location: [-700.0, 0.0, -200.0]}
    - {name: mid, type: free,   location: [-120.0, 0.0, -80.0]}
    - {name: v1,  type: vessel, location: [-20.0,  15.0, -14.0]}
    - {name: v2,  type: vessel, location: [-20.0, -15.0, -14.0]}
lines:
    - {name: main, endA: anc, endB: mid, type: chain, length: 600.0}
    - {name: b1,   endA: mid, endB: v1,  type: chain, length: 115.0}
    - {name: b2,   endA: mid, endB: v2,  type: chain, length: 115.0}
line_types:
    - {name: chain, diameter: 0.2, mass_density: 250.0, stiffness: 1.0e9}
"""
    )
    ms = system.compile_mooring(moor)
    assert ms.has_free
    r6 = jnp.zeros(6)
    pos = system._equilibrium_positions(ms, ms.params, r6)
    net = np.asarray(system._point_net_forces(ms, ms.params, pos))
    # free point (index 1) in equilibrium to ~1e-5 of the ~1e7 N tensions
    assert np.max(np.abs(net[1])) < 200.0
    # by symmetry its y stays ~0
    assert abs(float(pos[1, 1])) < 1e-3
    C = np.asarray(system.coupled_stiffness(ms, ms.params, r6))
    assert np.isfinite(C).all()
    assert C[0, 0] > 0


# ---------------------------------------------------------------------------
# line current drag (reference: mooring currentMod, raft_model.py:560-578)
# ---------------------------------------------------------------------------


def _with_drag(mooring, cd=2.0, cdax=0.1):
    import copy

    m = copy.deepcopy(mooring)
    for lt in m["line_types"]:
        lt["transverse_drag"] = cd
        lt["tangential_drag"] = cdax
    return m


def test_current_drag_changes_forces():
    """currentMod-equivalent path: a nonzero current with nonzero line Cd
    changes body force, stiffness, and tensions; zero current with drag
    coefficients parsed is identical to the no-drag baseline."""
    base = system.compile_mooring(OC3_MOORING)
    dragged = system.compile_mooring(_with_drag(OC3_MOORING))
    r6 = jnp.zeros(6)

    # parsing drag coefficients alone must change nothing
    F0 = np.asarray(system.body_forces(base, base.params, r6))
    F0d = np.asarray(system.body_forces(dragged, dragged.params, r6))
    np.testing.assert_allclose(F0d, F0, rtol=1e-12, atol=1e-8)

    U = np.array([1.5, 0.0, 0.0])
    pcur = system.params_with_current(dragged, U)
    Fc = np.asarray(system.body_forces(dragged, pcur, r6))
    # downstream drag load transfers partly onto the body: +x force grows
    assert Fc[0] > F0[0] + 1e3
    Tc = np.asarray(system.tensions(dragged, pcur, r6))
    T0 = np.asarray(system.tensions(dragged, dragged.params, r6))
    assert not np.allclose(Tc, T0, rtol=1e-4)
    Cc = np.asarray(system.coupled_stiffness(dragged, pcur, r6))
    assert np.all(np.isfinite(Cc))

    # zero Cd keeps the current from doing anything (the silent-wrong-answer
    # path VERDICT flagged now at least has explicit semantics + a warning
    # at the Model layer)
    pcur0 = system.params_with_current(base, U)
    Fc0 = np.asarray(system.body_forces(base, pcur0, r6))
    np.testing.assert_allclose(Fc0, F0, rtol=1e-12, atol=1e-8)


def test_current_tilted_frame_matches_rotated_gravity():
    """Free-hanging line with pure cross-line current: solving in the
    tilted effective-load frame must equal rotating the whole problem so
    the effective load is vertical and solving the plain catenary."""
    import dataclasses

    moor = yaml.safe_load(
        """
water_depth: 600
points:
    - {name: a, type: fixed,  location: [300.0, 0.0, -400.0]}
    - {name: v, type: vessel, location: [0.0, 0.0, -20.0]}
lines:
    - {name: l1, endA: a, endB: v, type: main, length: 520.0}
line_types:
    - {name: main, diameter: 0.09, mass_density: 77.7066, stiffness: 384.243e6,
       transverse_drag: 2.0, tangential_drag: 0.0}
"""
    )
    ms = system.compile_mooring(moor)
    assert float(ms.params.cb[0]) < 0  # hangs clear of the seabed
    r6 = jnp.zeros(6)

    U = np.array([0.0, 0.0, 0.0])
    w = float(ms.params.w[0])
    L = float(ms.params.L[0])

    # current in -x: drag q on the chord (anchor->vessel, mostly -x/+z
    # chord, current has a normal component)
    U = np.array([-0.8, 0.0, 0.0])
    pcur = system.params_with_current(ms, U)
    F_A, F_B, TA, TB = system._line_forces_at_points(
        ms, pcur, system.point_positions(ms, pcur, r6))

    # rebuild the same physics by hand: effective distributed load vector
    rA = np.array([300.0, 0.0, -400.0])
    rB = np.array([0.0, 0.0, -20.0])
    e = (rB - rA) / np.linalg.norm(rB - rA)
    Un = U - (U @ e) * e
    rho = float(ms.params.rho)
    q = 0.5 * rho * 0.09 * 2.0 * np.linalg.norm(Un) * Un
    f_d = q + np.array([0.0, 0.0, -w])
    w_eff = np.linalg.norm(f_d)
    zhat = -f_d / w_eff
    D = rB - rA
    zf = D @ zhat
    xvec = D - zf * zhat
    xf = np.linalg.norm(xvec)
    xhat = xvec / xf
    HA, VA, HF, VF = catenary.line_end_forces(
        jnp.asarray(xf), jnp.asarray(zf), jnp.asarray(L),
        ms.params.EA[0], jnp.asarray(w_eff), jnp.asarray(-1.0))
    F_A_ref = float(HA) * xhat + float(VA) * zhat
    F_B_ref = -float(HF) * xhat - float(VF) * zhat
    np.testing.assert_allclose(np.asarray(F_A)[0], F_A_ref, rtol=1e-8)
    np.testing.assert_allclose(np.asarray(F_B)[0], F_B_ref, rtol=1e-8)
    # global equilibrium: end reactions balance weight + drag load
    np.testing.assert_allclose(
        np.asarray(F_A)[0] + np.asarray(F_B)[0],
        np.array([0.0, 0.0, -w * L]) + q * L, rtol=1e-6)


def test_model_mooring_currentmod():
    """Model-level: a case with current changes the statics equilibrium
    when (and only when) design['mooring']['currentMod'] > 0."""
    from raft_tpu.core.model import Model
    from raft_tpu.designs import demo_spar

    case = {"wind_speed": 0.0, "wind_heading": 0.0, "turbulence": 0.0,
            "turbine_status": "parked", "yaw_misalign": 0.0,
            "wave_spectrum": "JONSWAP", "wave_period": 10.0,
            "wave_height": 4.0, "wave_heading": 0.0,
            "current_speed": 1.2, "current_heading": 0.0}

    def offsets(currentMod, cd, cdax=0.1):
        design = demo_spar(nw_freqs=(0.05, 0.4))
        design["mooring"] = _with_drag(design["mooring"], cd=cd, cdax=cdax)
        design["mooring"]["currentMod"] = currentMod
        model = Model(design)
        return np.array(model.solveStatics(dict(case)))

    off0 = offsets(0, 2.0)
    off1 = offsets(1, 2.0)
    # current drag on the lines shifts the surge equilibrium downstream
    assert abs(off1[0] - off0[0]) > 1e-3
    # and with zero drag coefficients currentMod>0 changes nothing (but warns)
    import warnings

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        off_nocd = offsets(1, 0.0, cdax=0.0)
    assert any("transverse_drag" in str(r.message) for r in rec)
    np.testing.assert_allclose(off_nocd, off0, atol=1e-8)


# ---------------------------------------------------------------------------
# bathymetry (reference: array_mooring bathymetry file, raft_model.py:85-89)
# ---------------------------------------------------------------------------


def test_bathymetry_file_and_contact(tmp_path):
    bath = tmp_path / "bath.txt"
    bath.write_text(
        "--- MoorPy Bathymetry Input File ---\n"
        "nGridX 3\n"
        "nGridY 2\n"
        "-1000.0 0.0 1000.0\n"
        "-1000.0  300.0 300.0 500.0\n"
        " 1000.0  300.0 300.0 500.0\n"
    )
    depth_at = system.read_bathymetry_file(str(bath))
    assert np.isclose(depth_at(-1000.0, 0.0), 300.0)
    assert np.isclose(depth_at(1000.0, 0.0), 500.0)
    assert np.isclose(depth_at(500.0, 0.0), 400.0)  # bilinear midpoint

    md = tmp_path / "lines.dat"
    md.write_text(
        "--- LINE TYPES ---\n"
        "name  d  m  EA  BA  EI  Cd  Ca  CdAx  CaAx\n"
        "(-)  (m) (kg/m) (N) (-) (-) (-) (-) (-) (-)\n"
        "chain 0.09 77.7 384.243e6 -1 0 1.2 1.0 0.1 0.0\n"
        "--- POINTS ---\n"
        "id attach x y z m v\n"
        "(-) (-) (m) (m) (m) (kg) (m3)\n"
        "1 Fixed  800.0 0.0 -300.0 0 0\n"
        "2 Body1  5.0 0.0 -70.0 0 0\n"
        "3 Fixed  -800.0 0.0 -300.0 0 0\n"
        "4 Body1  -5.0 0.0 -70.0 0 0\n"
        "--- LINES ---\n"
        "id type pointA pointB length n\n"
        "(-) (-) (-) (-) (m) (-)\n"
        "1 chain 1 2 850.0 20\n"
        "2 chain 3 4 850.0 20\n"
        "--- OPTIONS ---\n"
        "300.0 WtrDpth\n"
    )
    # uniform depth: both anchors at z=-300 rest on the 300 m seabed
    ms_flat = system.compile_moordyn_file(str(md), depth=300.0)
    assert float(ms_flat.params.cb[0]) >= 0 and float(ms_flat.params.cb[1]) >= 0
    # Cd columns parsed from the MoorDyn line-type table
    np.testing.assert_allclose(np.asarray(ms_flat.params.Cd_n), 1.2)
    np.testing.assert_allclose(np.asarray(ms_flat.params.Cd_ax), 0.1)

    # sloped seabed: at x=+800 the local depth is ~440 m, so the +x anchor
    # hangs clear; at x=-800 it is ~316 m, within tolerance of nothing —
    # still off the seabed; use a grid putting -800 exactly at 300 m
    bath2 = tmp_path / "bath2.txt"
    bath2.write_text(
        "--- MoorPy Bathymetry Input File ---\n"
        "nGridX 2\n"
        "nGridY 2\n"
        "-1000.0 1000.0\n"
        "-1000.0  300.0 500.0\n"
        " 1000.0  300.0 500.0\n"
    )
    ms_slope = system.compile_moordyn_file(
        str(md), depth=300.0, bathymetry=system.read_bathymetry_file(str(bath2)))
    assert float(ms_slope.params.cb[0]) < 0  # +x anchor: local depth 480 m
