"""Multi-sea-state (nWaves > 1) cases + example-script smoke tests."""

import subprocess
import sys

import numpy as np
import yaml

import raft_tpu

TEST_DATA = "/root/reference/tests/test_data"


def test_two_wave_headings():
    """A case with two simultaneous sea states: response rows per source,
    RMS-summed statistics (raft_fowt.py:998-1014, raft_model.py:1044-1083)."""
    with open(f"{TEST_DATA}/VolturnUS-S.yaml") as f:
        design = yaml.load(f, Loader=yaml.FullLoader)
    model = raft_tpu.Model(design)
    case = {"wind_speed": 0, "wind_heading": 0, "turbulence": 0,
            "turbine_status": "operating", "yaw_misalign": 0,
            "wave_spectrum": ["JONSWAP", "JONSWAP"],
            "wave_period": [10, 14], "wave_height": [4, 2],
            "wave_heading": [0, -30], "current_speed": 0, "current_heading": 0,
            "iCase": 0}
    model.solveStatics(dict(case))
    Xi = model.solveDynamics(dict(case))
    assert Xi.shape == (3, 6, model.nw)  # nWaves + 1 excitation sources
    assert np.all(np.isfinite(np.abs(Xi)))
    assert np.abs(Xi[0]).max() > 0 and np.abs(Xi[1]).max() > 0

    fowt = model.fowtList[0]
    res = {}
    fowt.saveTurbineOutputs(res, case)
    # two-source RMS must exceed either single source's contribution
    s0 = np.sqrt(0.5 * np.sum(np.abs(Xi[0, 0]) ** 2))
    s1 = np.sqrt(0.5 * np.sum(np.abs(Xi[1, 0]) ** 2))
    assert res["surge_std"] >= max(s0, s1) - 1e-12
    assert res["surge_std"] <= s0 + s1 + 1e-12


def test_example_scripts_run():
    """The self-contained example runs end to end as a subprocess."""
    out = subprocess.run(
        [sys.executable, "examples/example_from_yaml.py"],
        capture_output=True, text=True, timeout=900, cwd="/root/repo",
        env={"PYTHONPATH": "/root/repo", "PATH": "/usr/bin:/bin",
             "JAX_PLATFORMS": "cpu", "HOME": "/root"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "Natural periods" in out.stdout
