"""Native (C++) host engine vs the NumPy fallbacks.

The native layer (raft_tpu/native) is the counterpart of the Fortran
code the reference delegates to (CCBlade _bem, HAMS); these tests pin
it bit-for-bit (same quadrature rules) against the pure-NumPy paths.
Skipped wholesale when no C++ toolchain is available.
"""

import numpy as np
import pytest
from scipy.special import exp1, shichi

from raft_tpu import native
from raft_tpu.hydro import potential_bem
from raft_tpu.hydro.greens import _pv_integral

pytestmark = pytest.mark.skipif(native.lib() is None,
                                reason="no C++ toolchain / native lib")


def test_pv_against_analytic_a0():
    """I(0, V) = e^V [2*Shi(V) + E1(-V)] exactly."""
    V = np.array([-0.05, -0.2, -1.0, -3.0, -10.0])
    got = native.pv_points(np.zeros_like(V), V)
    shi, _ = shichi(V)
    exact = np.exp(V) * (2 * shi + exp1(-V))
    np.testing.assert_allclose(got, exact, rtol=1e-6)


def test_pv_matches_numpy_rule_rowwise():
    """Same quadrature rule as greens._pv_integral (per-A rows, the mode
    the table builder uses; mixed-A batches legitimately differ because
    the NumPy rule shares one tail grid per call)."""
    V = np.array([-0.01, -0.3, -2.0, -10.0, -50.0])
    for a in [0.0, 0.5, 3.0, 20.0, 80.0]:
        A = np.full_like(V, a)
        np.testing.assert_allclose(native.pv_points(A, V), _pv_integral(A, V),
                                   atol=1e-12)


def test_pv_table_matches_numpy_build():
    A_grid = 100.0 * np.linspace(0, 1, 40) ** 2
    V_grid = np.minimum(-60.0 * np.linspace(0, 1, 20) ** 2, -1e-6)
    tab = native.pv_table(A_grid, V_grid)
    ref = np.empty_like(tab)
    for i, a in enumerate(A_grid):
        ref[i, :] = _pv_integral(np.full(len(V_grid), a), V_grid)
    np.testing.assert_allclose(tab, ref, atol=1e-12)


def test_rankine_assembly_matches_numpy(monkeypatch):
    rng = np.random.default_rng(0)
    n = 40
    C = rng.normal(size=(n, 3))
    C[:, 2] = -np.abs(C[:, 2]) - 0.05
    A = np.abs(rng.normal(size=n)) * 0.1 + 0.01
    N = rng.normal(size=(n, 3))
    N /= np.linalg.norm(N, axis=1, keepdims=True)

    S0n, D0n = native.rankine_assemble(C, A, N, potential_bem.SELF_TERM_COEF)
    monkeypatch.setattr(native, "rankine_assemble", lambda *a: None)
    S0p, D0p = potential_bem._rankine_matrices(C, A, N)
    np.testing.assert_allclose(S0n, S0p, atol=1e-12)
    np.testing.assert_allclose(D0n, D0p, atol=1e-12)


def test_pv_fd_matches_numpy():
    """Finite-depth John-kernel PV quadrature: native vs NumPy rule."""
    from raft_tpu.hydro import greens_fd as gfd

    K, h = 0.8, 3.0
    k = gfd.wavenumber(K, h)
    rng = np.random.default_rng(0)
    R = rng.uniform(0.01, 5, 25)
    u = rng.uniform(-2 * h + 0.01, -0.01, 25)
    w = rng.uniform(0, h, 25)
    for kind, s in ((1, u), (2, w)):
        nat = native.pv_fd_points(R, s, K, h, k, kind)
        ref = gfd._pv_fd_numpy(R, s, K, h, k, kind)
        np.testing.assert_allclose(nat, ref, atol=1e-10)

    # adversarial pairing: a near-surface small-R point chunked with a
    # large-R point — the per-point tail truncation must hold (a
    # chunk-wide max-T grid differs here by ~2e-5)
    R_adv = np.array([0.05, 5.0])
    s_adv = np.array([-0.01, -0.01])
    nat = native.pv_fd_points(R_adv, s_adv, K, h, k, 1)
    ref = gfd._pv_fd_numpy(R_adv, s_adv, K, h, k, 1)
    np.testing.assert_allclose(nat, ref, atol=1e-10)

