"""Run-ledger telemetry (raft_tpu.obs): schema, sweep event streams,
report CLI, and the zero-overhead-off contract.

The observability layer's contract mirrors the executor's: arming
RAFT_TPU_LEDGER changes what gets RECORDED, never what gets computed —
ledger-on and ledger-off sweeps must be bit-identical with zero extra
XLA compiles.  The event stream itself must be schema-valid, totally
ordered (seq), complete (every dispatched chunk commits), and must
capture the fault/quarantine narrative when a chunk dies.
"""

import json
import logging
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu import sweep as sweep_mod
from raft_tpu.designs import demo_spar
from raft_tpu.obs import ledger as obs_ledger
from raft_tpu.obs import live as obs_live
from raft_tpu.obs import log as obs_log
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import report as obs_report
from raft_tpu.obs import schema as obs_schema
from raft_tpu.robust import STATUS_OK, STATUS_QUARANTINED

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]


def _sweep(**kw):
    kw.setdefault("n_iter", 8)
    kw.setdefault("chunk_size", 2)
    return sweep_mod.sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES, **kw)


def _ledger_sweep(tmp_path, monkeypatch, name, **kw):
    """Run one sweep with the ledger armed; return (out, events)."""
    ldir = tmp_path / name
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    out = _sweep(**kw)
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    runs = obs_ledger.list_runs(str(ldir))
    assert len(runs) == 1, runs
    return out, obs_ledger.read_events(runs[0]), runs[0]


def _names(events):
    return [ev["event"] for ev in events]


# ---------------------------------------------------------------------------
# schema + ledger primitives (no sweep)
# ---------------------------------------------------------------------------


def test_run_roundtrip_is_schema_valid(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path))
    run = obs_ledger.start_run("test", fingerprint={"k": "v"},
                               meta={"n": 3})
    assert run.enabled and run.run_id
    run.emit("plan", mode="resident", n_chunks=2, chunk_size=2)
    run.emit("transfer", direction="h2d", bytes=1024, what="x")
    # numpy scalars/arrays must serialize (emit happens under np types)
    run.emit("chunk_commit", chunk=np.int64(0), done=np.int64(2),
             n_designs=4, eta_s=np.float64(0.5))
    run.finish(ok=True, counts={"ok": 4})

    events = obs_ledger.read_events(run.path)
    assert obs_schema.validate_events(events) == []
    assert _names(events)[0] == "run_start"
    assert _names(events)[-1] == "run_end"
    assert events[0]["fingerprint"] == {"k": "v"}
    assert events[-1]["ok"] is True
    # seq is a strict total order
    seqs = [ev["seq"] for ev in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # emits after close are dropped, not raised on
    run.emit("plan", mode="late", n_chunks=1, chunk_size=1)
    assert len(obs_ledger.read_events(run.path)) == len(events)


def test_start_run_disabled_returns_null(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_LEDGER", raising=False)
    run = obs_ledger.start_run("test")
    assert run is obs_ledger.NULL_RUN and not run.enabled
    run.emit("anything")  # all no-ops
    run.finish(ok=True)
    run.close()
    assert obs_ledger.current_run() is obs_ledger.NULL_RUN


def test_schema_rejects_malformed_streams():
    ok = {"t": 1.0, "seq": 1, "event": "run_start",
          "run_id": "r", "kind": "test"}
    end = {"t": 2.0, "seq": 2, "event": "run_end", "ok": True}
    assert obs_schema.validate_events([ok, end]) == []

    errs = obs_schema.validate_events([ok, {"t": 1.5, "seq": 2,
                                            "event": "nonsense"}, end])
    assert any("unknown event" in e for e in errs)
    # missing required field
    errs = obs_schema.validate_events(
        [ok, {"t": 1.5, "seq": 2, "event": "transfer"},
         dict(end, seq=3)])
    assert any("missing required field" in e for e in errs)
    # seq must strictly increase
    errs = obs_schema.validate_events([ok, dict(end, seq=1)])
    assert any("seq not increasing" in e for e in errs)
    # stream must be bracketed run_start .. run_end
    errs = obs_schema.validate_events([ok])
    assert any("does not end with run_end" in e for e in errs)


def test_read_events_drops_truncated_tail(tmp_path):
    p = tmp_path / "t.jsonl"
    good = {"t": 1.0, "seq": 1, "event": "run_start",
            "run_id": "r", "kind": "k"}
    p.write_text(json.dumps(good) + "\n" + '{"t": 2.0, "seq": 2, "ev')
    events = obs_ledger.read_events(str(p))
    assert len(events) == 1 and events[0]["event"] == "run_start"


# ---------------------------------------------------------------------------
# sweep event streams
# ---------------------------------------------------------------------------


def test_sweep_ledger_lifecycle_and_report(tmp_path, monkeypatch, capsys):
    out, events, path = _ledger_sweep(tmp_path, monkeypatch, "l1")
    assert (out["status"] == STATUS_OK).all()
    assert obs_schema.validate_events(events) == []
    names = _names(events)

    # lifecycle ordering
    assert names[0] == "run_start" and names[-1] == "run_end"
    for earlier, later in [("template_build", "plan"),
                           ("plan", "chunk_dispatch"),
                           ("chunk_dispatch", "chunk_commit"),
                           ("chunk_commit", "health_report"),
                           ("health_report", "run_end")]:
        assert names.index(earlier) < names.index(later), (earlier, later)
    # phase_stats are flushed at finish, before run_end
    assert names.index("phase_stats") < names.index("run_end")

    start = events[0]
    assert start["kind"] == "sweep"
    assert start["fingerprint"]["n_designs"] == 4
    assert start["fingerprint"]["n_cases"] == len(STATES)
    assert events[-1]["ok"] is True and events[-1]["counts"]["ok"] == 4

    by = {}
    for ev in events:
        by.setdefault(ev["event"], []).append(ev)
    plan = by["plan"][0]
    assert plan["n_chunks"] == 2 and plan["chunk_size"] == 2
    # every compile_start has a matching compile_end (or the memo hit)
    if "compile_start" in by:
        assert sorted(e["key"] for e in by["compile_start"]) == \
            sorted(e["key"] for e in by["compile_end"])
    else:
        assert "compile_cache" in by
    # health report counts agree with the sweep output
    assert by["health_report"][0]["counts"]["ok"] == 4
    # phase events streamed + aggregated
    stat_names = {e["name"] for e in by["phase_stats"]}
    assert any(n.startswith("sweep") for n in stat_names)
    for e in by["phase_stats"]:
        assert e["calls"] >= 1
        assert e["min"] <= e["mean"] <= e["max"]

    # the report CLI renders it and validates clean
    assert obs_report.main([path, "--validate"]) == 0
    text = capsys.readouterr().out
    for section in ("phase waterfall", "compile vs execute",
                    "data movement", "chunk pipeline", "health"):
        assert section in text, section
    assert events[0]["run_id"] in text


def test_chunk_events_complete_across_pipeline_depths(tmp_path, monkeypatch):
    """Every dispatched chunk must fetch and commit exactly once at any
    pipeline depth, commits must account for all designs, and in_flight
    must respect the depth cap."""
    _sweep()  # warm the executables so both runs take the same path
    for depth in (1, 3):
        monkeypatch.setenv("RAFT_TPU_PIPELINE", str(depth))
        _, events, _ = _ledger_sweep(tmp_path, monkeypatch, f"d{depth}")
        assert obs_schema.validate_events(events) == []
        by = {}
        for ev in events:
            by.setdefault(ev["event"], []).append(ev)

        dispatches = by["chunk_dispatch"]
        commits = by["chunk_commit"]
        assert [e["chunk"] for e in dispatches] == [0, 1]
        assert sorted(e["chunk"] for e in by["chunk_fetch"]) == [0, 1]
        assert sorted(e["chunk"] for e in commits) == [0, 1]
        assert sum(e["n_real"] for e in dispatches) == 4
        assert max(e["done"] for e in commits) == 4
        for e in commits:
            assert e["eta_s"] >= 0.0
        in_flight = [e["in_flight"] for e in dispatches]
        assert max(in_flight) <= depth
        if depth == 1:
            assert in_flight == [1, 1]
        # per-chunk ordering: dispatch(c) < fetch(c) < commit(c)
        seq_of = lambda evs, c: next(e["seq"] for e in evs if e["chunk"] == c)
        for c in (0, 1):
            assert seq_of(dispatches, c) < seq_of(by["chunk_fetch"], c) \
                < seq_of(commits, c)
        # d2h movement was accounted (h2d transfer events only appear on
        # a COLD resident upload; these warm sweeps hit the resident
        # cache, so requiring one here would be wrong)
        assert all(e["bytes"] > 0 for e in by["chunk_fetch"])
    monkeypatch.delenv("RAFT_TPU_PIPELINE")


def test_fault_injected_sweep_records_quarantine_narrative(
        tmp_path, monkeypatch, capsys):
    """ISSUE acceptance: a fault-injected 2-chunk sweep with the ledger
    armed yields a renderable event log carrying the full fault ->
    bisect -> quarantine -> status narrative."""
    _sweep()  # warm
    poison = 1

    def hook(idx, dispatch):
        if (np.asarray(idx) == poison).any():
            raise RuntimeError("injected chunk fault")
        return dispatch(idx)

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    with pytest.warns(RuntimeWarning, match="isolating faults"):
        out, events, path = _ledger_sweep(tmp_path, monkeypatch, "fault")
    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)

    assert out["status"][poison] == STATUS_QUARANTINED
    assert obs_schema.validate_events(events) == []
    by = {}
    for ev in events:
        by.setdefault(ev["event"], []).append(ev)

    fault = by["chunk_fault"][0]
    assert fault["start"] == 0 and fault["stop"] == 2
    assert "injected chunk fault" in fault["error"]
    # the 2-design chunk is bisected, the poisoned design quarantined
    assert by["quarantine_bisect"][0]["n"] == 2
    assert by["design_quarantined"][0]["designs"] == [poison]
    trans = {e["to"] for e in by["status_transition"]}
    assert "quarantined" in trans
    assert any("isolating faults" in e["message"]
               for e in by["warning"])
    assert by["health_report"][0]["counts"]["quarantined"] == 1
    assert events[-1]["event"] == "run_end" and events[-1]["ok"] is True
    # narrative ordering: fault before quarantine before the health rollup
    names = _names(events)
    assert names.index("chunk_fault") < names.index("design_quarantined") \
        < names.index("health_report")

    # the ledger renders (the whole point of a flight recorder)
    assert obs_report.main([path]) == 0
    text = capsys.readouterr().out
    assert "quarantine" in text and "injected chunk fault" in text


def test_run_end_records_failure(tmp_path, monkeypatch):
    """A sweep that dies still closes its ledger with ok=false + error
    (the crash-forensics contract)."""
    _sweep()  # warm

    def hook(idx, dispatch):
        raise KeyboardInterrupt("operator abort")

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path / "dead"))
    with pytest.raises(KeyboardInterrupt):
        _sweep()
    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)
    monkeypatch.delenv("RAFT_TPU_LEDGER")

    runs = obs_ledger.list_runs(str(tmp_path / "dead"))
    assert len(runs) == 1
    events = obs_ledger.read_events(runs[0])
    assert obs_schema.validate_events(events) == []
    assert events[-1]["event"] == "run_end"
    assert events[-1]["ok"] is False
    assert "operator abort" in events[-1]["error"]
    # no dangling active run leaks into the next sweep
    assert obs_ledger.current_run() is obs_ledger.NULL_RUN


# ---------------------------------------------------------------------------
# zero-overhead-off: telemetry must not change results or compiles
# ---------------------------------------------------------------------------


@pytest.mark.sentinel
def test_ledger_on_off_bit_identical_no_recompile(tmp_path, monkeypatch):
    """ISSUE acceptance: sweeps with the ledger unset are bit-identical
    to ledger-on sweeps, and arming telemetry compiles ZERO additional
    XLA programs."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    monkeypatch.delenv("RAFT_TPU_LEDGER", raising=False)
    base = _sweep()  # warm: compiles + memoizes the executables

    with RecompileSentinel() as s:
        snap = s.snapshot()
        off = _sweep()
        s.assert_no_recompile(snap, "ledger-off sweep")
        on, events, _ = _ledger_sweep(tmp_path, monkeypatch, "on")
        s.assert_no_recompile(snap, "ledger-on sweep")

    for a, b in ((base, off), (off, on)):
        np.testing.assert_array_equal(a["motion_std"], b["motion_std"])
        np.testing.assert_array_equal(a["AxRNA_std"], b["AxRNA_std"])
        np.testing.assert_array_equal(a["status"], b["status"])
    assert obs_schema.validate_events(events) == []
    # the ledger-on run observed its (cache-hit) compile state honestly
    assert any(n in ("compile_cache", "compile_end") for n in _names(events))


# ---------------------------------------------------------------------------
# logging funnel
# ---------------------------------------------------------------------------


def test_logger_records_stamp_run_id(tmp_path, monkeypatch):
    logger = obs_log.get_logger("test.stamp")
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    h = Capture()
    logger.addHandler(h)
    logger.setLevel(logging.INFO)
    try:
        logger.info("outside any run")
        monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path))
        run = obs_ledger.start_run("test")
        logger.info("inside the run")
        run.finish(ok=True)
        logger.info("after close")
    finally:
        logger.removeHandler(h)

    assert [r.run_id for r in records] == ["-", run.run_id, "-"]


def test_warn_funnel_hits_all_three_channels(tmp_path, monkeypatch):
    logger = obs_log.get_logger("test.warnfunnel")
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path))
    run = obs_ledger.start_run("test")
    with pytest.warns(UserWarning, match="tri-channel"):
        obs_log.warn(logger, "tri-channel message", UserWarning)
    run.finish(ok=True)
    events = obs_ledger.read_events(run.path)
    warning = [e for e in events if e["event"] == "warning"]
    assert warning and warning[0]["message"] == "tri-channel message"


def test_display_funnel_prints(capsys):
    logger = obs_log.get_logger("test.display")
    obs_log.display(logger, "progress line")
    assert "progress line" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# live metrics registry (obs.metrics) + endpoint (obs.live)
# ---------------------------------------------------------------------------


@pytest.fixture
def metrics_env(monkeypatch):
    """Arm the registry for one test and restore pristine global state."""
    monkeypatch.delenv("RAFT_TPU_LEDGER", raising=False)
    monkeypatch.setenv("RAFT_TPU_METRICS", "1")
    obs_metrics.reset()
    yield obs_metrics
    obs_live.stop_server()
    obs_metrics.reset()


def test_metrics_instruments_and_prometheus_render(metrics_env):
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("t_total", "a counter", ("kind",))
    c.inc(kind="a")
    c.inc(2, kind="b")
    assert c.value(kind="b") == 2
    with pytest.raises(ValueError):
        c.inc(-1, kind="a")  # counters only go up
    with pytest.raises(ValueError):
        c.inc(kind="a", extra="nope")  # undeclared label
    g = reg.gauge("t_depth", "a gauge")
    g.set(3)
    g.dec()
    assert g.value() == 2
    h = reg.histogram("t_lat", "a histogram", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.1)   # == edge lands IN the 0.1 bucket (le semantics)
    h.observe(5.0)   # overflow -> +Inf only
    assert h.count() == 3
    # idempotent re-declare; conflicting re-declare raises
    assert reg.counter("t_total", "a counter", ("kind",)) is c
    with pytest.raises(ValueError):
        reg.gauge("t_total", "now a gauge")

    text = reg.render_prometheus()
    assert "# TYPE t_total counter" in text
    assert 't_total{kind="b"} 2' in text
    assert "# TYPE t_lat histogram" in text
    assert 't_lat_bucket{le="0.1"} 2' in text
    assert 't_lat_bucket{le="1"} 2' in text
    assert 't_lat_bucket{le="+Inf"} 3' in text
    assert "t_lat_count 3" in text
    assert "t_lat_sum 5.15" in text


def test_observe_event_drives_live_status(metrics_env):
    """A synthetic event stream (same vocabulary a real run emits) must
    populate the instruments and the /status /runs state."""
    obs_metrics.observe_event("run_start", {
        "t": 1.0, "run_id": "r1", "kind": "sweep",
        "fingerprint": {"n_designs": 4, "n_cases": 2}})
    obs_metrics.observe_event("plan", {"n_chunks": 2, "chunk_size": 2,
                                       "mode": "resident"})
    obs_metrics.observe_event("chunk_dispatch", {"chunk": 0, "in_flight": 1})
    obs_metrics.observe_event("phase", {"name": "sweep/chunks/compute",
                                        "seconds": 0.02})
    obs_metrics.observe_event("exec_cache_hit", {"key": "partA"})
    obs_metrics.observe_event("chunk_commit", {"chunk": 0, "done": 2,
                                               "n_designs": 4, "eta_s": 0.5})
    obs_metrics.observe_event("status_transition",
                              {"designs": [3], "to": "non_converged"})

    st = obs_metrics.status_snapshot()["active"]
    assert st["run_id"] == "r1" and st["phase"] == "chunks"
    assert st["n_designs"] == 4 and st["n_chunks"] == 2
    assert st["chunks_done"] == 1 and st["designs_done"] == 2
    assert st["eta_s"] == 0.5
    assert st["status_counts"] == {"non_converged": 1}

    m = obs_metrics.std()
    assert m.chunks_dispatched.value() == 1
    assert m.chunks_committed.value() == 1
    assert m.stage_seconds.count(stage="compute") == 1
    assert m.exec_cache.value(outcome="hit") == 1

    obs_metrics.observe_event("run_end", {"t": 9.0, "ok": True,
                                          "counts": {"ok": 4}})
    assert obs_metrics.status_snapshot()["active"] is None
    runs = obs_metrics.recent_runs()
    assert runs[0]["run_id"] == "r1" and runs[0]["ok"] is True
    assert m.runs_finished.value(kind="sweep", ok="true") == 1
    assert m.chunks_in_flight.value() == 0


def test_metrics_only_run_is_fileless_and_feeds_registry(metrics_env):
    """Ledger off + metrics on: start_run hands out a file-less Run so
    the single emission point feeds the registry without touching disk."""
    assert obs_ledger.observing()
    run = obs_ledger.start_run("sweep", fingerprint={"n_designs": 4})
    assert run.enabled and run.path is None
    run.emit("chunk_dispatch", chunk=0, start=0, stop=2, n_real=2,
             in_flight=1)
    run.finish(ok=True)
    m = obs_metrics.std()
    assert m.chunks_dispatched.value() == 1
    assert obs_metrics.recent_runs()[0]["kind"] == "sweep"
    # both consumers off -> NULL_RUN (zero-overhead path intact)
    import os

    os.environ.pop("RAFT_TPU_METRICS", None)
    obs_live.stop_server()
    assert not obs_ledger.observing()
    assert obs_ledger.start_run("sweep") is obs_ledger.NULL_RUN
    os.environ["RAFT_TPU_METRICS"] = "1"


@pytest.mark.sentinel
def test_metrics_on_off_bit_identical_no_recompile(monkeypatch):
    """ISSUE acceptance: sweeps with metrics armed are bit-identical to
    metrics-off sweeps and compile ZERO additional XLA programs — the
    registry never touches jit/lowering."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    monkeypatch.delenv("RAFT_TPU_LEDGER", raising=False)
    monkeypatch.delenv("RAFT_TPU_METRICS", raising=False)
    base = _sweep()  # warm: compiles + memoizes the executables

    obs_metrics.reset()
    try:
        with RecompileSentinel() as s:
            snap = s.snapshot()
            off = _sweep()
            s.assert_no_recompile(snap, "metrics-off sweep")
            monkeypatch.setenv("RAFT_TPU_METRICS", "1")
            on = _sweep()
            s.assert_no_recompile(snap, "metrics-on sweep")

        for a, b in ((base, off), (off, on)):
            np.testing.assert_array_equal(a["motion_std"], b["motion_std"])
            np.testing.assert_array_equal(a["AxRNA_std"], b["AxRNA_std"])
            np.testing.assert_array_equal(a["status"], b["status"])
        # the armed sweep actually fed the registry
        m = obs_metrics.std()
        assert m.chunks_committed.value() == 2
        assert m.stage_seconds.count(stage="compute") >= 2
        assert obs_metrics.recent_runs()[0]["ok"] is True
        monkeypatch.delenv("RAFT_TPU_METRICS")
    finally:
        obs_metrics.reset()


def test_live_endpoint_scrapes_mid_sweep(metrics_env, monkeypatch):
    """ISSUE acceptance: with RAFT_TPU_METRICS_PORT set, /metrics and
    /status answer from another thread WHILE a sweep runs."""
    _sweep()  # warm so the threaded sweep takes the fast memoized path
    monkeypatch.setenv("RAFT_TPU_METRICS_PORT", "0")  # ephemeral bind
    obs_metrics.reset()

    paused, release = threading.Event(), threading.Event()

    def hook(idx, dispatch):
        if (np.asarray(idx) == 1).any():
            paused.set()
            assert release.wait(30), "scraper never released the sweep"
        return dispatch(idx)

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    box = {}

    def run_sweep():
        try:
            box["out"] = _sweep()
        except BaseException as e:  # noqa: BLE001 - surfaced after join
            box["err"] = e

    t = threading.Thread(target=run_sweep, daemon=True)
    t.start()
    try:
        assert paused.wait(60), "sweep never reached the paused chunk"
        host, port = obs_live.server_address()
        base = f"http://{host}:{port}"

        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            assert r.status == 200
            text = r.read().decode()
        assert "# TYPE raft_chunk_stage_seconds histogram" in text
        assert "# TYPE raft_exec_cache_total counter" in text
        assert "raft_chunks_dispatched_total" in text
        assert "raft_run_active 1" in text

        with urllib.request.urlopen(f"{base}/status", timeout=10) as r:
            status = json.loads(r.read().decode())
        active = status["active"]
        assert active is not None and active["kind"] == "sweep"
        assert active["phase"] == "chunks"
        assert active["n_designs"] == 4
        assert "eta_s" in active  # live ETA slot (set at first commit)
    finally:
        release.set()
        t.join(timeout=120)
        monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)

    assert "err" not in box, box.get("err")
    assert (box["out"]["status"] == STATUS_OK).all()

    # after the run: /status idles, /runs remembers it
    host, port = obs_live.server_address()
    with urllib.request.urlopen(f"http://{host}:{port}/status",
                                timeout=10) as r:
        assert json.loads(r.read().decode())["active"] is None
    with urllib.request.urlopen(f"http://{host}:{port}/runs",
                                timeout=10) as r:
        runs = json.loads(r.read().decode())["runs"]
    assert runs and runs[0]["kind"] == "sweep" and runs[0]["ok"] is True


def test_live_endpoint_404(metrics_env, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_METRICS_PORT", "0")
    srv = obs_live.ensure_server()
    assert srv is not None
    try:
        urllib.request.urlopen(f"{srv.url}/nope", timeout=10)
        assert False, "expected HTTP 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_device_memory_reports_supported_flag(tmp_path, monkeypatch):
    """Satellite fix: a backend without memory_stats() yields
    supported=false (distinguishing 'zero bytes' from 'not measured')
    plus a one-time warning, never an error."""
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path))
    run = obs_ledger.start_run("test")

    class NoStats:
        def memory_stats(self):
            return None

        def __str__(self):
            return "FakeCpu:0"

    obs_ledger.emit_device_memory(run, device=NoStats(), what="t1")
    obs_ledger.emit_device_memory(run, device=NoStats(), what="t2")
    run.finish(ok=True)
    events = obs_ledger.read_events(run.path)
    mems = [e for e in events if e["event"] == "device_memory"]
    assert len(mems) == 2
    assert all(m["supported"] is False for m in mems)
    assert all(m["bytes_in_use"] is None for m in mems)
    # warn_once: exactly one warning despite two probes of the device
    warns = [e for e in events if e["event"] == "warning"
             and "memory_stats" in e["message"]]
    assert len(warns) == 1
