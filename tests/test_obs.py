"""Run-ledger telemetry (raft_tpu.obs): schema, sweep event streams,
report CLI, and the zero-overhead-off contract.

The observability layer's contract mirrors the executor's: arming
RAFT_TPU_LEDGER changes what gets RECORDED, never what gets computed —
ledger-on and ledger-off sweeps must be bit-identical with zero extra
XLA compiles.  The event stream itself must be schema-valid, totally
ordered (seq), complete (every dispatched chunk commits), and must
capture the fault/quarantine narrative when a chunk dies.
"""

import json
import logging

import numpy as np
import pytest

from raft_tpu import sweep as sweep_mod
from raft_tpu.designs import demo_spar
from raft_tpu.obs import ledger as obs_ledger
from raft_tpu.obs import log as obs_log
from raft_tpu.obs import report as obs_report
from raft_tpu.obs import schema as obs_schema
from raft_tpu.robust import STATUS_OK, STATUS_QUARANTINED

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]


def _sweep(**kw):
    kw.setdefault("n_iter", 8)
    kw.setdefault("chunk_size", 2)
    return sweep_mod.sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES, **kw)


def _ledger_sweep(tmp_path, monkeypatch, name, **kw):
    """Run one sweep with the ledger armed; return (out, events)."""
    ldir = tmp_path / name
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(ldir))
    out = _sweep(**kw)
    monkeypatch.delenv("RAFT_TPU_LEDGER")
    runs = obs_ledger.list_runs(str(ldir))
    assert len(runs) == 1, runs
    return out, obs_ledger.read_events(runs[0]), runs[0]


def _names(events):
    return [ev["event"] for ev in events]


# ---------------------------------------------------------------------------
# schema + ledger primitives (no sweep)
# ---------------------------------------------------------------------------


def test_run_roundtrip_is_schema_valid(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path))
    run = obs_ledger.start_run("test", fingerprint={"k": "v"},
                               meta={"n": 3})
    assert run.enabled and run.run_id
    run.emit("plan", mode="resident", n_chunks=2, chunk_size=2)
    run.emit("transfer", direction="h2d", bytes=1024, what="x")
    # numpy scalars/arrays must serialize (emit happens under np types)
    run.emit("chunk_commit", chunk=np.int64(0), done=np.int64(2),
             n_designs=4, eta_s=np.float64(0.5))
    run.finish(ok=True, counts={"ok": 4})

    events = obs_ledger.read_events(run.path)
    assert obs_schema.validate_events(events) == []
    assert _names(events)[0] == "run_start"
    assert _names(events)[-1] == "run_end"
    assert events[0]["fingerprint"] == {"k": "v"}
    assert events[-1]["ok"] is True
    # seq is a strict total order
    seqs = [ev["seq"] for ev in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # emits after close are dropped, not raised on
    run.emit("plan", mode="late", n_chunks=1, chunk_size=1)
    assert len(obs_ledger.read_events(run.path)) == len(events)


def test_start_run_disabled_returns_null(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_LEDGER", raising=False)
    run = obs_ledger.start_run("test")
    assert run is obs_ledger.NULL_RUN and not run.enabled
    run.emit("anything")  # all no-ops
    run.finish(ok=True)
    run.close()
    assert obs_ledger.current_run() is obs_ledger.NULL_RUN


def test_schema_rejects_malformed_streams():
    ok = {"t": 1.0, "seq": 1, "event": "run_start",
          "run_id": "r", "kind": "test"}
    end = {"t": 2.0, "seq": 2, "event": "run_end", "ok": True}
    assert obs_schema.validate_events([ok, end]) == []

    errs = obs_schema.validate_events([ok, {"t": 1.5, "seq": 2,
                                            "event": "nonsense"}, end])
    assert any("unknown event" in e for e in errs)
    # missing required field
    errs = obs_schema.validate_events(
        [ok, {"t": 1.5, "seq": 2, "event": "transfer"},
         dict(end, seq=3)])
    assert any("missing required field" in e for e in errs)
    # seq must strictly increase
    errs = obs_schema.validate_events([ok, dict(end, seq=1)])
    assert any("seq not increasing" in e for e in errs)
    # stream must be bracketed run_start .. run_end
    errs = obs_schema.validate_events([ok])
    assert any("does not end with run_end" in e for e in errs)


def test_read_events_drops_truncated_tail(tmp_path):
    p = tmp_path / "t.jsonl"
    good = {"t": 1.0, "seq": 1, "event": "run_start",
            "run_id": "r", "kind": "k"}
    p.write_text(json.dumps(good) + "\n" + '{"t": 2.0, "seq": 2, "ev')
    events = obs_ledger.read_events(str(p))
    assert len(events) == 1 and events[0]["event"] == "run_start"


# ---------------------------------------------------------------------------
# sweep event streams
# ---------------------------------------------------------------------------


def test_sweep_ledger_lifecycle_and_report(tmp_path, monkeypatch, capsys):
    out, events, path = _ledger_sweep(tmp_path, monkeypatch, "l1")
    assert (out["status"] == STATUS_OK).all()
    assert obs_schema.validate_events(events) == []
    names = _names(events)

    # lifecycle ordering
    assert names[0] == "run_start" and names[-1] == "run_end"
    for earlier, later in [("template_build", "plan"),
                           ("plan", "chunk_dispatch"),
                           ("chunk_dispatch", "chunk_commit"),
                           ("chunk_commit", "health_report"),
                           ("health_report", "run_end")]:
        assert names.index(earlier) < names.index(later), (earlier, later)
    # phase_stats are flushed at finish, before run_end
    assert names.index("phase_stats") < names.index("run_end")

    start = events[0]
    assert start["kind"] == "sweep"
    assert start["fingerprint"]["n_designs"] == 4
    assert start["fingerprint"]["n_cases"] == len(STATES)
    assert events[-1]["ok"] is True and events[-1]["counts"]["ok"] == 4

    by = {}
    for ev in events:
        by.setdefault(ev["event"], []).append(ev)
    plan = by["plan"][0]
    assert plan["n_chunks"] == 2 and plan["chunk_size"] == 2
    # every compile_start has a matching compile_end (or the memo hit)
    if "compile_start" in by:
        assert sorted(e["key"] for e in by["compile_start"]) == \
            sorted(e["key"] for e in by["compile_end"])
    else:
        assert "compile_cache" in by
    # health report counts agree with the sweep output
    assert by["health_report"][0]["counts"]["ok"] == 4
    # phase events streamed + aggregated
    stat_names = {e["name"] for e in by["phase_stats"]}
    assert any(n.startswith("sweep") for n in stat_names)
    for e in by["phase_stats"]:
        assert e["calls"] >= 1
        assert e["min"] <= e["mean"] <= e["max"]

    # the report CLI renders it and validates clean
    assert obs_report.main([path, "--validate"]) == 0
    text = capsys.readouterr().out
    for section in ("phase waterfall", "compile vs execute",
                    "data movement", "chunk pipeline", "health"):
        assert section in text, section
    assert events[0]["run_id"] in text


def test_chunk_events_complete_across_pipeline_depths(tmp_path, monkeypatch):
    """Every dispatched chunk must fetch and commit exactly once at any
    pipeline depth, commits must account for all designs, and in_flight
    must respect the depth cap."""
    _sweep()  # warm the executables so both runs take the same path
    for depth in (1, 3):
        monkeypatch.setenv("RAFT_TPU_PIPELINE", str(depth))
        _, events, _ = _ledger_sweep(tmp_path, monkeypatch, f"d{depth}")
        assert obs_schema.validate_events(events) == []
        by = {}
        for ev in events:
            by.setdefault(ev["event"], []).append(ev)

        dispatches = by["chunk_dispatch"]
        commits = by["chunk_commit"]
        assert [e["chunk"] for e in dispatches] == [0, 1]
        assert sorted(e["chunk"] for e in by["chunk_fetch"]) == [0, 1]
        assert sorted(e["chunk"] for e in commits) == [0, 1]
        assert sum(e["n_real"] for e in dispatches) == 4
        assert max(e["done"] for e in commits) == 4
        for e in commits:
            assert e["eta_s"] >= 0.0
        in_flight = [e["in_flight"] for e in dispatches]
        assert max(in_flight) <= depth
        if depth == 1:
            assert in_flight == [1, 1]
        # per-chunk ordering: dispatch(c) < fetch(c) < commit(c)
        seq_of = lambda evs, c: next(e["seq"] for e in evs if e["chunk"] == c)
        for c in (0, 1):
            assert seq_of(dispatches, c) < seq_of(by["chunk_fetch"], c) \
                < seq_of(commits, c)
        # d2h movement was accounted (h2d transfer events only appear on
        # a COLD resident upload; these warm sweeps hit the resident
        # cache, so requiring one here would be wrong)
        assert all(e["bytes"] > 0 for e in by["chunk_fetch"])
    monkeypatch.delenv("RAFT_TPU_PIPELINE")


def test_fault_injected_sweep_records_quarantine_narrative(
        tmp_path, monkeypatch, capsys):
    """ISSUE acceptance: a fault-injected 2-chunk sweep with the ledger
    armed yields a renderable event log carrying the full fault ->
    bisect -> quarantine -> status narrative."""
    _sweep()  # warm
    poison = 1

    def hook(idx, dispatch):
        if (np.asarray(idx) == poison).any():
            raise RuntimeError("injected chunk fault")
        return dispatch(idx)

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    with pytest.warns(RuntimeWarning, match="isolating faults"):
        out, events, path = _ledger_sweep(tmp_path, monkeypatch, "fault")
    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)

    assert out["status"][poison] == STATUS_QUARANTINED
    assert obs_schema.validate_events(events) == []
    by = {}
    for ev in events:
        by.setdefault(ev["event"], []).append(ev)

    fault = by["chunk_fault"][0]
    assert fault["start"] == 0 and fault["stop"] == 2
    assert "injected chunk fault" in fault["error"]
    # the 2-design chunk is bisected, the poisoned design quarantined
    assert by["quarantine_bisect"][0]["n"] == 2
    assert by["design_quarantined"][0]["designs"] == [poison]
    trans = {e["to"] for e in by["status_transition"]}
    assert "quarantined" in trans
    assert any("isolating faults" in e["message"]
               for e in by["warning"])
    assert by["health_report"][0]["counts"]["quarantined"] == 1
    assert events[-1]["event"] == "run_end" and events[-1]["ok"] is True
    # narrative ordering: fault before quarantine before the health rollup
    names = _names(events)
    assert names.index("chunk_fault") < names.index("design_quarantined") \
        < names.index("health_report")

    # the ledger renders (the whole point of a flight recorder)
    assert obs_report.main([path]) == 0
    text = capsys.readouterr().out
    assert "quarantine" in text and "injected chunk fault" in text


def test_run_end_records_failure(tmp_path, monkeypatch):
    """A sweep that dies still closes its ledger with ok=false + error
    (the crash-forensics contract)."""
    _sweep()  # warm

    def hook(idx, dispatch):
        raise KeyboardInterrupt("operator abort")

    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", hook)
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path / "dead"))
    with pytest.raises(KeyboardInterrupt):
        _sweep()
    monkeypatch.setattr(sweep_mod, "_CHUNK_EXEC_HOOK", None)
    monkeypatch.delenv("RAFT_TPU_LEDGER")

    runs = obs_ledger.list_runs(str(tmp_path / "dead"))
    assert len(runs) == 1
    events = obs_ledger.read_events(runs[0])
    assert obs_schema.validate_events(events) == []
    assert events[-1]["event"] == "run_end"
    assert events[-1]["ok"] is False
    assert "operator abort" in events[-1]["error"]
    # no dangling active run leaks into the next sweep
    assert obs_ledger.current_run() is obs_ledger.NULL_RUN


# ---------------------------------------------------------------------------
# zero-overhead-off: telemetry must not change results or compiles
# ---------------------------------------------------------------------------


@pytest.mark.sentinel
def test_ledger_on_off_bit_identical_no_recompile(tmp_path, monkeypatch):
    """ISSUE acceptance: sweeps with the ledger unset are bit-identical
    to ledger-on sweeps, and arming telemetry compiles ZERO additional
    XLA programs."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    monkeypatch.delenv("RAFT_TPU_LEDGER", raising=False)
    base = _sweep()  # warm: compiles + memoizes the executables

    with RecompileSentinel() as s:
        snap = s.snapshot()
        off = _sweep()
        s.assert_no_recompile(snap, "ledger-off sweep")
        on, events, _ = _ledger_sweep(tmp_path, monkeypatch, "on")
        s.assert_no_recompile(snap, "ledger-on sweep")

    for a, b in ((base, off), (off, on)):
        np.testing.assert_array_equal(a["motion_std"], b["motion_std"])
        np.testing.assert_array_equal(a["AxRNA_std"], b["AxRNA_std"])
        np.testing.assert_array_equal(a["status"], b["status"])
    assert obs_schema.validate_events(events) == []
    # the ledger-on run observed its (cache-hit) compile state honestly
    assert any(n in ("compile_cache", "compile_end") for n in _names(events))


# ---------------------------------------------------------------------------
# logging funnel
# ---------------------------------------------------------------------------


def test_logger_records_stamp_run_id(tmp_path, monkeypatch):
    logger = obs_log.get_logger("test.stamp")
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    h = Capture()
    logger.addHandler(h)
    logger.setLevel(logging.INFO)
    try:
        logger.info("outside any run")
        monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path))
        run = obs_ledger.start_run("test")
        logger.info("inside the run")
        run.finish(ok=True)
        logger.info("after close")
    finally:
        logger.removeHandler(h)

    assert [r.run_id for r in records] == ["-", run.run_id, "-"]


def test_warn_funnel_hits_all_three_channels(tmp_path, monkeypatch):
    logger = obs_log.get_logger("test.warnfunnel")
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path))
    run = obs_ledger.start_run("test")
    with pytest.warns(UserWarning, match="tri-channel"):
        obs_log.warn(logger, "tri-channel message", UserWarning)
    run.finish(ok=True)
    events = obs_ledger.read_events(run.path)
    warning = [e for e in events if e["event"] == "warning"]
    assert warning and warning[0]["message"] == "tri-channel message"


def test_display_funnel_prints(capsys):
    logger = obs_log.get_logger("test.display")
    obs_log.display(logger, "progress line")
    assert "progress line" in capsys.readouterr().out
