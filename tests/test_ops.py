"""Math-kernel parity tests.

Golden values mirror the reference's unit test corpus
(/root/reference/tests/test_helpers.py) so the JAX kernels can be checked
for exact numerical parity (rtol 1e-5) with the original NumPy routines.
"""

import numpy as np
import pytest
from numpy.testing import assert_allclose

from raft_tpu.ops import frustum, transforms, waves
from raft_tpu.schema import get_from_dict


def test_frustum_vcv():
    # circular (test_helpers.py:14-18)
    V, hc = frustum.frustum_vcv_circ(2.0, 1.0, 2.0)
    assert_allclose([V, hc], [3.665191429188092, 0.7857142857142856], rtol=1e-05)
    # rectangular (test_helpers.py:20-23)
    V, hc = frustum.frustum_vcv_rect([2.0, 1.0], [1.0, 0.5], 2.0)
    assert_allclose([V, hc], [2.3333333333333335, 0.7857142857142857], rtol=1e-05)
    # degenerate
    V, hc = frustum.frustum_vcv_circ(0.0, 0.0, 2.0)
    assert_allclose([V, hc], [0.0, 0.0])


def test_kinematics_from_modes():
    # test_helpers.py:26-38
    r = np.array([2.0, 2.0, 2.0])
    w = np.array([0.5, 0.75])
    Xi = np.array(
        [
            [1, 2 + 1j],
            [0.1 + 0.2j, 0.3 + 0.4j],
            [0.5 + 0.6j, 0.7 + 0.8j],
            [0.9 + 1.0j, 1.1 + 1.2j],
            [1.3 + 1.4j, 1.5 + 1.6j],
            [1.7 + 1.8j, 1.9 + 2.0j],
        ]
    )
    desired = np.array(
        [
            [
                [0.2 - 8.00000000e-01j, 1.2 + 2.00000000e-01j],
                [1.7 + 1.80000000e00j, 1.9 + 2.00000000e00j],
                [-0.3 - 2.00000000e-01j, -0.1 - 2.22044605e-16j],
            ],
            [
                [4.00000000e-01 + 0.1j, -1.50000000e-01 + 0.9j],
                [-9.00000000e-01 + 0.85j, -1.50000000e00 + 1.425j],
                [1.00000000e-01 - 0.15j, 1.66533454e-16 - 0.075j],
            ],
            [
                [-0.05 + 2.0000000e-01j, -0.675 - 1.1250000e-01j],
                [-0.425 - 4.5000000e-01j, -1.06875 - 1.1250000e00j],
                [0.075 + 5.0000000e-02j, 0.05625 + 1.2490009e-16j],
            ],
        ]
    )
    dr, v, a = waves.kinematics_from_modes(r, Xi, w)
    assert_allclose(np.array([dr, v, a]), desired, rtol=1e-05, atol=1e-12)


def test_wave_number_and_kinematics():
    # test_helpers.py:41-69
    w = np.array([0.1, 0.25, 0.5, 0.75])
    zeta0 = np.full(4, 0.2)
    beta, h = 30.0, 200.0
    r = np.array([30.0, 45.0, -20.0])

    k = waves.wave_number(w, h)
    assert_allclose(k, [0.00233623, 0.0071452, 0.02548611, 0.05733945], rtol=1e-05)

    desired_u = np.array(
        [
            [0.00690971 + 0.00064489j, 0.00732697 + 0.00214361j, 0.00488759 + 0.00787284j, -0.00480898 + 0.00555819j],
            [-0.04425901 - 0.00413072j, -0.04693167 - 0.01373052j, -0.03130665 - 0.05042812j, 0.03080313 - 0.03560204j],
            [-0.00166131 + 0.01780023j, -0.01192503 + 0.04076042j, -0.05102840 + 0.03167931j, -0.03603330 - 0.03117625j],
        ]
    )
    desired_pDyn = np.array(
        [
            1963.730340920 + 183.276331860j,
            1703.156386190 + 498.282218140j,
            637.171137130 + 1026.342526750j,
            -417.980049950 + 483.098446900j,
        ]
    )
    u, ud, pDyn = waves.wave_kinematics(zeta0, beta, w, k, h, r)
    assert_allclose(u, desired_u, rtol=1e-05, atol=1e-9)
    assert_allclose(ud, 1j * w * desired_u, rtol=1e-05, atol=1e-9)
    assert_allclose(pDyn, desired_pDyn, rtol=1e-05)

    # dry node gives zeros
    u2, ud2, p2 = waves.wave_kinematics(zeta0, beta, w, k, h, np.array([0.0, 0.0, 5.0]))
    assert_allclose(np.abs(u2), 0.0)
    assert_allclose(np.abs(p2), 0.0)

    # batched nodes: stack wet+dry and confirm rows match the single-node runs
    rr = np.stack([r, np.array([0.0, 0.0, 5.0])])
    ub, _, pb = waves.wave_kinematics(zeta0, beta, w, k, h, rr)
    assert ub.shape == (2, 3, 4)
    assert_allclose(ub[0], u, rtol=1e-12)
    assert_allclose(pb[1], 0.0)


def test_wave_kinematics_f32_grad_finite():
    # deep-water nodes (kh >> 89.4) must not poison f32 gradients via the
    # masked shallow-water branch (inf/inf = NaN under grad-of-where)
    import jax
    import jax.numpy as jnp

    w = jnp.asarray([1.5], dtype=jnp.float32)
    k = jnp.asarray([0.3], dtype=jnp.float32)  # k*h = 300 with h=1000
    zeta0 = jnp.asarray([1.0], dtype=jnp.complex64)

    def p_at_depth(z):
        r = jnp.stack([jnp.float32(0.0), jnp.float32(0.0), z])
        _, _, p = waves.wave_kinematics(zeta0, 0.0, w, k, jnp.float32(1000.0), r)
        return jnp.real(p)[0]

    g = jax.grad(p_at_depth)(jnp.float32(-5.0))
    assert np.isfinite(float(g))


def test_transform_force_rejects_ambiguous_orientation():
    F = np.zeros(3)
    with pytest.raises(ValueError):
        transforms.transform_force(np.zeros(4))
    with pytest.raises(ValueError):
        transforms.transform_force(F, orientation=np.zeros((2, 3)))


def test_small_rotate():
    # test_helpers.py:72-77
    r = np.array([1.0, 2.0, 3.0])
    th = np.array([5 + 3j, 3 + 5j, 4 + 3j]) * (np.pi / 180.0)
    rt = transforms.small_rotate(r, th)
    desired = np.array([0.01745329 + 0.15707963j, -0.19198622 - 0.10471976j, 0.12217305 + 0.01745329j])
    assert_allclose(rt, desired, rtol=1e-05)


def test_outer3():
    # test_helpers.py:80-85
    v = np.array([0.7 + 1.2j, 1.5 + 0.4j, 3.0 + 2.3j])
    desired = np.array(
        [
            [-0.95 + 1.68j, 0.57 + 2.08j, -0.66 + 5.21j],
            [0.57 + 2.08j, 2.09 + 1.2j, 3.58 + 4.65j],
            [-0.66 + 5.21j, 3.58 + 4.65j, 3.71 + 13.8j],
        ]
    )
    assert_allclose(transforms.outer3(v), desired, rtol=1e-05)


def test_translate_force_3to6():
    # test_helpers.py:88-94
    Fin = np.array([0.5 + 3j, 2.0 + 1.5j, 3.0 + 0.7j])
    r = np.array([1.0, 2.0, 3.0])
    desired = np.array([0.5 + 3.0j, 2.0 + 1.5j, 3.0 + 0.7j, 0.0 - 3.1j, -1.5 + 8.3j, 1.0 - 4.5j])
    assert_allclose(transforms.translate_force_3to6(Fin, r), desired, rtol=1e-05)


def test_transform_force():
    # test_helpers.py:97-120
    offset = np.array([10.0, 20.0, 30.0])
    f_in = np.array([0.5 + 3j, 2.0 + 1.5j, 3.0 + 0.7j])
    F_in = np.array([1.2 + 0.3j, 0.4 + 1.5j, 2.3 + 0.7j, 0.5 + 0.9j, 1.1 + 0.2j, 0.7 + 1.4j])
    orient_3 = np.array([0.1, 0.2, 0.3])
    rotMat = transforms.rotation_matrix(orient_3)

    desired = np.array(
        [
            0.57300698 + 2.54908178j,
            1.94679387 + 2.27765615j,
            3.02186311 + 0.23337633j,
            2.03344603 - 63.66215798j,
            -13.02842176 + 74.13869023j,
            8.00779917 - 28.20507416j,
        ]
    )
    assert_allclose(transforms.transform_force(f_in, offset=offset, orientation=orient_3), desired, rtol=1e-05)
    assert_allclose(transforms.transform_force(f_in, offset=offset, orientation=rotMat), desired, rtol=1e-05)

    desired6 = np.array(
        [
            1.51572022 + 2.10897023e-02j,
            0.64512428 + 1.49565656e00j,
            2.04362591 + 7.69783522e-01j,
            21.83717669 - 2.83806906e01j,
            26.20635997 - 6.66493243e00j,
            -23.17224939 + 1.57407763e01j,
        ]
    )
    assert_allclose(transforms.transform_force(F_in, offset=offset, orientation=orient_3), desired6, rtol=1e-05)
    assert_allclose(transforms.transform_force(F_in, offset=offset, orientation=rotMat), desired6, rtol=1e-05)


def test_translate_matrix_3to6():
    # test_helpers.py:123-136
    Min = np.array([[0.73, 2.41, 3.88], [1.25, 9.12, 5.79], [5.37, 7.94, 8.63]])
    r = np.array([10.0, 20.0, 30.0])
    desired = np.array(
        [
            [7.300e-01, 2.410e00, 3.880e00, 5.300e00, -1.690e01, 9.500e00],
            [1.250e00, 9.120e00, 5.790e00, -1.578e02, -2.040e01, 6.620e01],
            [5.370e00, 7.940e00, 8.630e00, -6.560e01, 7.480e01, -2.800e01],
            [5.300e00, -1.578e02, -6.560e01, 3.422e03, 2.108e03, -2.546e03],
            [-1.690e01, -2.040e01, 7.480e01, 8.150e02, -1.255e03, 5.650e02],
            [9.500e00, 6.620e01, -2.800e01, -1.684e03, 1.340e02, 4.720e02],
        ]
    )
    assert_allclose(transforms.translate_matrix_3to6(Min, r), desired, rtol=1e-05)


def test_translate_matrix_6to6():
    # test_helpers.py:139-155
    Min = np.array(
        [
            [0.57, 0.64, 0.88, 0.12, 0.34, 0.56],
            [2.03, -13.02, 8.00, 0.78, 0.90, 0.12],
            [1.11, -0.15, 0.10, 0.34, 0.56, 0.78],
            [0.12, 0.78, 0.34, 0.90, 0.12, 0.34],
            [0.34, 0.90, 0.56, 0.12, 0.34, 0.56],
            [0.56, 0.12, 0.78, 0.34, 0.56, 0.78],
        ]
    )
    r = np.array([10.0, 20.0, 30.0])
    desired = np.array(
        [
            [5.70000e-01, 6.40000e-01, 8.80000e-01, -1.48000e00, 8.64000e00, -4.44000e00],
            [2.03000e00, -1.30200e01, 8.00000e00, 5.51380e02, -1.82000e01, -1.70680e02],
            [1.11000e00, -1.50000e-01, 1.00000e-01, 6.84000e00, 3.28600e01, -2.29200e01],
            [-1.48000e00, 5.51380e02, 6.84000e00, -1.64203e04, 1.20352e03, 4.66774e03],
            [8.64000e00, -1.82000e01, 3.28600e01, -1.28480e02, -6.44600e01, 9.87600e01],
            [-4.44000e00, -1.70680e02, -2.29200e01, 5.55574e03, -3.45240e02, -1.62722e03],
        ]
    )
    assert_allclose(transforms.translate_matrix_6to6(Min, r), desired, rtol=1e-05)


def test_rotate_matrix6():
    # test_helpers.py:158-175
    rotMat = transforms.rotation_matrix(np.array([0.1, 0.2, 0.3]))
    Min = np.array(
        [
            [0.57, 0.64, 0.88, 0.12, 0.34, 0.56],
            [2.03, -13.02, 8.00, 0.78, 0.90, 0.12],
            [1.11, -0.15, 0.10, 0.34, 0.56, 0.78],
            [0.12, 0.78, 0.34, 0.90, 0.12, 0.34],
            [0.34, 0.90, 0.56, 0.12, 0.34, 0.56],
            [0.56, 0.12, 0.78, 0.34, 0.56, 0.78],
        ]
    )
    desired = np.array(
        [
            [-1.23327412, 4.08056795, -0.95870608, 0.06516703, 0.15206293, 0.66964386],
            [7.03270577, -11.42123791, 6.09625616, 0.51524892, 1.11098643, 0.18118973],
            [1.67312218, -1.16775529, 0.30451203, 0.34805446, 0.62871201, 0.62384654],
            [0.06516703, 0.51524892, 0.34805446, 0.86182628, 0.37858592, 0.16449501],
            [0.15206293, 1.11098643, 0.62871201, 0.37858592, 0.40719201, 0.55131878],
            [0.66964386, 0.18118973, 0.62384654, 0.16449501, 0.55131878, 0.75098172],
        ]
    )
    assert_allclose(transforms.rotate_matrix6(Min, rotMat), desired, rtol=1e-05)


def test_rot_from_vectors():
    # test_helpers.py:194-200
    rotMat = transforms.rotation_matrix(np.array([0.1, 0.2, 0.3]))
    A = np.array([5.0, 0.0, 0.0])
    B = rotMat @ A
    R = transforms.rot_from_vectors(A, B)
    assert_allclose(B, R @ A, rtol=1e-05)
    # parallel vectors → identity
    assert_allclose(transforms.rot_from_vectors(A, A), np.eye(3), atol=1e-12)


def test_jonswap_matches_reference_formula():
    ws = np.linspace(0.03, 2.5, 100)
    Hs, Tp = 6.0, 12.0
    # reference implementation transcribed in NumPy (helpers.JONSWAP)
    TpOvrSqrtHs = Tp / np.sqrt(Hs)
    if TpOvrSqrtHs <= 3.6:
        Gamma = 5.0
    elif TpOvrSqrtHs >= 5.0:
        Gamma = 1.0
    else:
        Gamma = np.exp(5.75 - 1.15 * TpOvrSqrtHs)
    f = 0.5 / np.pi * ws
    fpOvrf4 = (Tp * f) ** -4.0
    C = 1.0 - 0.287 * np.log(Gamma)
    Sigma = 0.07 * (f <= 1.0 / Tp) + 0.09 * (f > 1.0 / Tp)
    Alpha = np.exp(-0.5 * ((f * Tp - 1.0) / Sigma) ** 2)
    S_ref = 0.5 / np.pi * C * 0.3125 * Hs * Hs * fpOvrf4 / f * np.exp(-1.25 * fpOvrf4) * Gamma**Alpha

    assert_allclose(waves.jonswap(ws, Hs, Tp), S_ref, rtol=1e-10)
    assert_allclose(waves.jonswap(ws, Hs, Tp, gamma=0), S_ref, rtol=1e-10)
    # explicit gamma (low-frequency tail underflows to exactly 0, as in the
    # reference formula — just require non-negative & finite)
    S1 = np.asarray(waves.jonswap(ws, Hs, Tp, gamma=1.0))
    assert np.all(S1 >= 0) and np.all(np.isfinite(S1))


def test_psd_rms_rao():
    rng = np.random.default_rng(0)
    xi = rng.normal(size=(3, 8)) + 1j * rng.normal(size=(3, 8))
    dw = 0.05
    assert_allclose(waves.rms(xi), np.sqrt(0.5 * np.sum(np.abs(xi) ** 2)), rtol=1e-12)
    assert_allclose(waves.psd(xi, dw), np.sum(0.5 * np.abs(xi) ** 2 / dw, axis=0), rtol=1e-12)
    zeta = np.array([0.0, 1.0, 2.0, 1e-8, 4.0, 5.0, 6.0, 7.0])
    r = waves.rao(xi, zeta)
    assert_allclose(np.asarray(r)[:, 0], 0.0)
    assert_allclose(np.asarray(r)[:, 2], xi[:, 2] / 2.0, rtol=1e-12)


def test_get_from_dict():
    d = {"a": 1.0, "b": [1.0, 2.0, 3.0], "c": [[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]}
    assert get_from_dict(d, "a") == 1.0
    assert_allclose(get_from_dict(d, "a", shape=3), [1.0, 1.0, 1.0])
    assert_allclose(get_from_dict(d, "b", shape=3), [1.0, 2.0, 3.0])
    assert_allclose(get_from_dict(d, "c", shape=3, index=0), [1.0, 3.0, 5.0])
    assert_allclose(get_from_dict(d, "c", shape=[3, 2]), [[1, 2], [3, 4], [5, 6]])
    assert_allclose(get_from_dict(d, "b", shape=[2, 3]), [[1, 2, 3], [1, 2, 3]])
    assert get_from_dict(d, "missing", default=7.0) == 7.0
    assert_allclose(get_from_dict(d, "missing", shape=2, default=7.0), [7.0, 7.0])
    with pytest.raises(ValueError):
        get_from_dict(d, "missing")
    with pytest.raises(ValueError):
        get_from_dict(d, "b", shape=4)


def test_rotation_matrix_properties():
    rpy = np.array([0.1, -0.2, 0.3])
    R = np.asarray(transforms.rotation_matrix(rpy))
    assert_allclose(R @ R.T, np.eye(3), atol=1e-12)
    assert_allclose(np.linalg.det(R), 1.0, rtol=1e-12)
    # yaw-only rotation about z
    Rz = np.asarray(transforms.rotation_matrix(np.array([0.0, 0.0, np.pi / 2])))
    assert_allclose(Rz @ np.array([1.0, 0, 0]), np.array([0, 1.0, 0]), atol=1e-12)
