"""Perf-observatory tests: static cost extraction, the roofline join,
graceful degradation, and the perf-off bit-identity sentinel.

Covers the ISSUE-12 contracts:

* ``costmodel.extract_cost`` degrades to ``supported=false`` (never an
  error) when ``cost_analysis()`` returns None, raises, or omits the
  ``flops`` / ``bytes accessed`` keys — the ``emit_device_memory``
  pattern, warn_once included;
* ``obs.perf.utilization_report`` math on synthetic events with a known
  device-spec row (TPU v4): achieved rates, AI, MFU, busy/stall split,
  and the compute / bandwidth / pipeline-stall / unknown bound classes;
* end-to-end: a perf-armed demo sweep emits ``program_cost`` events on
  both the cold (compile-service) and warm (template-memo) paths, the
  report renders a Roofline section, history ingests ``util_*``
  metrics, and the straggler report carries bound annotations;
* sentinel: perf-on vs perf-off sweeps are bit-identical with zero
  extra real XLA compiles (cost extraction is AOT-read-only).
"""

import numpy as np
import pytest

from raft_tpu import sweep as sweep_mod
from raft_tpu.analysis import costmodel
from raft_tpu.designs import demo_spar
from raft_tpu.obs import history as obs_history
from raft_tpu.obs import ledger as obs_ledger
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import perf as obs_perf
from raft_tpu.obs import report as obs_report
from raft_tpu.obs import timeline as obs_timeline

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]


def _sweep(**kw):
    kw.setdefault("n_iter", 8)
    kw.setdefault("chunk_size", 2)
    return sweep_mod.sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES, **kw)


# ---------------------------------------------------------------------------
# extract_cost: graceful degradation (fake compiled objects)
# ---------------------------------------------------------------------------


class _Compiled:
    """Fake jax Compiled with controllable cost_analysis behavior."""

    def __init__(self, ca):
        self._ca = ca

    def cost_analysis(self):
        if isinstance(self._ca, Exception):
            raise self._ca
        return self._ca

    def memory_analysis(self):
        return None


def test_extract_cost_supported_list_and_dict():
    for ca in ([{"flops": 10.0, "bytes accessed": 4.0}],
               {"flops": 10.0, "bytes accessed": 4.0}):
        cost = costmodel.extract_cost(_Compiled(ca))
        assert cost["supported"] is True
        assert cost["flops"] == 10.0
        assert cost["bytes_accessed"] == 4.0
        assert cost["error"] is None


@pytest.mark.parametrize("ca", [
    None,                                    # backend returns nothing
    [],                                      # empty properties list
    RuntimeError("no cost analysis"),        # backend raises
    [{"bytes accessed": 4.0}],               # missing 'flops'
    [{"flops": 10.0}],                       # missing 'bytes accessed'
    [{"flops": "many", "bytes accessed": 4.0}],  # non-numeric
], ids=["none", "empty", "raises", "no-flops", "no-bytes", "non-numeric"])
def test_extract_cost_degrades_not_raises(ca):
    cost = costmodel.extract_cost(_Compiled(ca))
    assert cost["supported"] is False
    assert cost["flops"] is None
    assert cost["bytes_accessed"] is None
    assert cost["error"]


def test_observe_program_unsupported_stamps_event_and_warns_once(
        tmp_path, monkeypatch):
    """An uncostable executable yields program_cost(supported=false) on
    EVERY observation but only one warning — never a sweep failure."""
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path))
    run = obs_ledger.start_run("test")
    bad = _Compiled(RuntimeError("backend says no"))
    for _ in range(2):
        out = costmodel.observe_program(
            "degraded-prog", "tag", None, bad, run=run)
        assert out is not None and out["supported"] is False
    run.finish(ok=True)
    events = obs_ledger.read_events(run.path)
    costs = [e for e in events if e["event"] == "program_cost"]
    assert len(costs) == 2
    assert all(e["supported"] is False for e in costs)
    assert all(e["flops"] is None for e in costs)
    assert all("backend says no" in (e.get("error") or "") for e in costs)
    warns = [e for e in events if e["event"] == "warning"
             and "degraded-prog" in e.get("message", "")]
    assert len(warns) == 1


def test_observe_program_never_raises_on_garbage():
    # not even a cost_analysis attribute: the hook must swallow it
    out = costmodel.observe_program("junk-prog", "t", None, object())
    assert out is not None and out["supported"] is False


# ---------------------------------------------------------------------------
# device specs + the roofline join on synthetic events
# ---------------------------------------------------------------------------


def test_device_spec_matching():
    assert obs_perf.device_spec("TPU v4")["peak_flops"] == 275e12
    # longest-prefix: v5 lite must not match the v5p/v5 rows
    assert obs_perf.device_spec("TPU v5 lite")["peak_flops"] == 197e12
    assert obs_perf.device_spec("TPU v5p")["peak_flops"] == 459e12
    assert obs_perf.device_spec("cpu") is None
    assert obs_perf.device_spec(None) is None
    assert obs_perf.device_spec("TPU v99 quantum") is None


def _cost_event(program, flops, nbytes, kind="TPU v4", n=2):
    return {"event": "program_cost", "t": 99.0, "program": program,
            "supported": True, "flops": flops, "bytes_accessed": nbytes,
            "peak_bytes": 1000, "source": "compile",
            "backend": "tpu", "device_kind": kind, "n_devices": n}


def _chunk_events(spans, nbytes=100):
    out = []
    for i, (t_d, t_f) in enumerate(spans):
        out.append({"event": "chunk_dispatch", "t": t_d, "chunk": i,
                    "start": 0, "stop": 2, "n_real": 2, "in_flight": 1,
                    "devices": [0, 1]})
        out.append({"event": "chunk_fetch", "t": t_f, "chunk": i,
                    "bytes": nbytes, "per_device": {"0": nbytes // 2,
                                                    "1": nbytes // 2}})
    return out


def test_utilization_report_math_bandwidth_bound():
    # AI = 1e10 / 2e9 = 5 << v4 machine balance (~224) -> bandwidth
    events = [_cost_event("A", 6e9, 1e9), _cost_event("B", 4e9, 1e9)]
    events += _chunk_events([(100.0, 100.5), (100.5, 101.0)])
    u = obs_perf.utilization_report(events)
    s = u["summary"]
    assert u["supported"] is True
    assert s["chunk_flops"] == 1e10 and s["chunk_bytes"] == 2e9
    assert s["ai"] == pytest.approx(5.0)
    assert s["span_s"] == pytest.approx(1.0)
    assert s["busy_s"] == pytest.approx(1.0)
    assert s["stall_frac"] == pytest.approx(0.0)
    assert s["total_flops"] == 2e10
    assert s["achieved_flops"] == pytest.approx(2e10)
    # 2 devices x 275 TF (summary values are rounded to 6 decimals)
    assert s["mfu"] == pytest.approx(2e10 / (2 * 275e12), abs=5e-7)
    assert s["bound"] == "bandwidth"
    assert all(c["bound"] == "bandwidth" for c in u["chunks"])
    assert u["per_device"]["0"]["share"] == pytest.approx(0.5)


def test_utilization_report_compute_bound():
    events = [_cost_event("A", 1e15, 1e9)]  # AI = 1e6 >> balance
    events += _chunk_events([(0.0, 1.0)])
    s = obs_perf.utilization_report(events)["summary"]
    assert s["bound"] == "compute"


def test_utilization_report_pipeline_stall_dominates():
    # 1.0 s busy in a 2.5 s span: 60% idle -> stall-bound regardless
    # of the statics
    events = [_cost_event("A", 1e15, 1e9)]
    events += _chunk_events([(100.0, 100.5), (102.0, 102.5)])
    s = obs_perf.utilization_report(events)["summary"]
    assert s["stall_frac"] == pytest.approx(0.6)
    assert s["bound"] == "pipeline-stall"


def test_utilization_report_unknown_device_is_honest():
    events = [_cost_event("A", 1e10, 1e9, kind="cpu")]
    events += _chunk_events([(0.0, 1.0)])
    u = obs_perf.utilization_report(events)
    s = u["summary"]
    assert s["achieved_flops"] == pytest.approx(1e10)  # rates still real
    assert "mfu" not in s                              # peak unknown
    assert s["bound"] == "unknown"
    assert u["chunks"][0]["bound"] == "unknown"


def test_utilization_report_unsupported_costs():
    events = [dict(_cost_event("A", None, None), supported=False,
                   flops=None, bytes_accessed=None, error="nope")]
    events += _chunk_events([(0.0, 1.0)])
    u = obs_perf.utilization_report(events)
    assert u["supported"] is False
    assert u["summary"]["supported"] is False
    assert "achieved_flops" not in u["summary"]
    # walls are still accounted even uncosted
    assert u["summary"]["span_s"] == pytest.approx(1.0)


def test_interval_union_overlapping_spans():
    # pipeline_depth > 1: overlapping dispatch->fetch windows must not
    # double-count busy time
    assert obs_perf._interval_union(
        [(0.0, 2.0), (1.0, 3.0), (5.0, 6.0)]) == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# end-to-end: perf-armed sweep -> ledger -> report/history/timeline
# ---------------------------------------------------------------------------


@pytest.fixture()
def perf_ledger(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path / "ledger"))
    monkeypatch.setenv("RAFT_TPU_PERF", "1")
    out = _sweep()
    runs = obs_ledger.list_runs(str(tmp_path / "ledger"))
    assert len(runs) == 1
    return out, obs_ledger.read_events(runs[0]), runs[0]


def test_perf_sweep_emits_program_costs(perf_ledger):
    _, events, _ = perf_ledger
    costs = [e for e in events if e["event"] == "program_cost"]
    progs = {e["program"] for e in costs}
    assert progs == {"A", "B"}
    # CPU XLA implements cost_analysis: the demo sweep must be costed
    assert all(e["supported"] for e in costs)
    assert all(e["flops"] > 0 for e in costs)
    assert all(e["bytes_accessed"] > 0 for e in costs)
    assert all(e["source"] in ("compile", "memo") for e in costs)
    # schema round-trip
    from raft_tpu.obs import schema as obs_schema
    assert obs_schema.validate_events(events) == []


def test_report_renders_roofline_section(perf_ledger):
    _, events, _ = perf_ledger
    text = "\n".join(obs_report.render(events))
    assert "== roofline" in text
    # per-program statics visible
    assert "A" in text and "B" in text
    assert "achieved" in text
    assert "bound" in text


def test_history_ingests_utilization(perf_ledger, tmp_path):
    _, _, path = perf_ledger
    rec = obs_history.summarize_ledger(path)
    m = rec["metrics"]
    assert m["util_supported"] == 1
    assert m["util_achieved_gflops"] > 0
    assert m["util_ai"] > 0
    # the CI pin: a costed run satisfies util_supported>=1
    res = obs_history.run_check([rec], requires=["util_supported>=1"])
    assert res["ok"], res


def test_straggler_report_carries_bound_annotations(perf_ledger):
    _, events, _ = perf_ledger
    rep = obs_timeline.straggler_report(events)
    assert rep["utilization"] is not None
    assert rep["utilization"]["supported"] is True
    assert rep["chunks"]
    for c in rep["chunks"]:
        assert "bound" in c and "idle_s" in c
    text = obs_timeline.format_stragglers(rep)
    assert "run bound:" in text


def test_bench_utilization_ingest():
    """history.summarize_bench lifts detail.utilization into util_*."""
    line = {"metric": "bench", "value": 10.0, "t": 1.0,
            "detail": {"utilization": {"supported": True,
                                       "achieved_gflops": 12.5,
                                       "ai": 0.2, "stall_frac": 0.1},
                       "mesh": {"designs_per_sec_per_device": 4.0}}}
    rec = obs_history.summarize_bench(line)
    assert rec["metrics"]["util_supported"] == 1
    assert rec["metrics"]["util_achieved_gflops"] == 12.5
    assert rec["metrics"]["designs_per_sec_per_device"] == 4.0


def test_warm_sweep_reemits_costs_from_memo(tmp_path, monkeypatch):
    """Repeat sweeps never touch the compile service; the template-memo
    hook must still cost them (source='memo')."""
    _sweep()  # ensure the memo holds this shape
    monkeypatch.setenv("RAFT_TPU_LEDGER", str(tmp_path / "warm"))
    monkeypatch.setenv("RAFT_TPU_PERF", "1")
    _sweep()
    runs = obs_ledger.list_runs(str(tmp_path / "warm"))
    events = obs_ledger.read_events(runs[-1])
    costs = [e for e in events if e["event"] == "program_cost"]
    assert {e["program"] for e in costs} == {"A", "B"}
    assert all(e["source"] == "memo" for e in costs)
    assert all(e["supported"] for e in costs)


# ---------------------------------------------------------------------------
# the acceptance sentinel: perf on/off bit-identity, zero extra compiles
# ---------------------------------------------------------------------------


@pytest.mark.sentinel
def test_perf_on_off_bit_identical_no_recompile(monkeypatch):
    """ISSUE-12 acceptance: sweeps with the perf observatory armed are
    bit-identical to perf-off sweeps and compile ZERO additional XLA
    programs — cost extraction only reads already-built executables."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    monkeypatch.delenv("RAFT_TPU_LEDGER", raising=False)
    monkeypatch.delenv("RAFT_TPU_PERF", raising=False)
    base = _sweep()  # warm: compiles + memoizes the executables

    obs_metrics.reset()
    costmodel.take_results()  # drain observations left by other tests
    try:
        with RecompileSentinel() as s:
            snap = s.snapshot()
            off = _sweep()
            s.assert_no_recompile(snap, "perf-off sweep")
            monkeypatch.setenv("RAFT_TPU_PERF", "1")
            monkeypatch.setenv("RAFT_TPU_METRICS", "1")
            on = _sweep()
            s.assert_no_recompile(snap, "perf-on sweep")

        for a, b in ((base, off), (off, on)):
            np.testing.assert_array_equal(a["motion_std"], b["motion_std"])
            np.testing.assert_array_equal(a["AxRNA_std"], b["AxRNA_std"])
            np.testing.assert_array_equal(a["status"], b["status"])
        # the armed sweep actually extracted costs: the session
        # collector is the witness (no ledger run in this test)
        results = [(k, c) for k, c in costmodel.take_results()
                   if k in ("A", "B")]
        assert {k for k, _ in results} == {"A", "B"}
        assert all(c["supported"] for _, c in results)
        monkeypatch.delenv("RAFT_TPU_PERF")
        monkeypatch.delenv("RAFT_TPU_METRICS")
    finally:
        obs_metrics.reset()
