"""Per-phase timing: nesting, accumulation, and thread isolation.

The sweep's pipelined executor runs a background checkpoint-writer
thread (and AOT compile workers) that record their own phases; the
nesting stack must be thread-local or concurrent phases splice into the
main thread's hierarchy and pop each other's frames.
"""

import threading

from raft_tpu import profiling


def test_nested_phases_accumulate():
    profiling.reset()
    with profiling.phase("outer"):
        with profiling.phase("inner"):
            pass
        with profiling.phase("inner"):
            pass
    rep = profiling.report()
    assert set(rep) == {"outer", "outer/inner"}
    assert profiling.counts()["outer/inner"] == 2
    assert rep["outer"] >= rep["outer/inner"] >= 0.0
    profiling.reset()
    assert profiling.report() == {}


def test_phase_stack_is_thread_local():
    """A phase opened on a worker thread must not become the prefix of a
    main-thread phase that happens to run inside its time window (the
    old process-global stack recorded 'a/b' here and popped frames
    across threads)."""
    profiling.reset()
    in_a = threading.Event()
    release = threading.Event()

    def worker():
        with profiling.phase("writer_phase"):
            in_a.set()
            release.wait(timeout=5.0)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    assert in_a.wait(timeout=5.0)
    # main thread enters a phase while the worker's phase is open
    with profiling.phase("main_phase"):
        with profiling.phase("sub"):
            pass
    release.set()
    t.join(timeout=5.0)

    keys = set(profiling.report())
    assert keys == {"writer_phase", "main_phase", "main_phase/sub"}
    profiling.reset()


def test_stats_and_summary_per_call_statistics():
    """stats() carries per-call min/mean/max; summary() renders them
    with a %-of-total column; report() keeps its {phase: seconds}
    contract untouched."""
    profiling.reset()
    with profiling.phase("outer"):
        for _ in range(3):
            with profiling.phase("inner"):
                pass
    st = profiling.stats()
    assert st["outer/inner"]["calls"] == 3
    v = st["outer/inner"]
    assert v["min"] <= v["mean"] <= v["max"]
    assert abs(v["mean"] - v["total"] / 3) < 1e-12
    # report() stays the stable flat {phase: seconds} mapping
    rep = profiling.report()
    assert set(rep) == {"outer", "outer/inner"}
    assert all(isinstance(x, float) for x in rep.values())

    text = profiling.summary()
    header, *rows = text.splitlines()
    for col in ("calls", "total_s", "min_s", "mean_s", "max_s", "%"):
        assert col in header
    assert any("outer/inner" in r for r in rows)
    # %-of-total is computed against top-level phases: 'outer' is 100%
    outer_row = next(r for r in rows
                     if r.startswith("outer ") or r.startswith("outer  "))
    assert "100.0%" in outer_row
    profiling.reset()
    assert profiling.summary() == "(no phases recorded)"


def test_listeners_observe_phase_exits_and_survive_errors():
    profiling.reset()
    seen = []

    def good(name, seconds):
        seen.append((name, seconds))

    def bad(name, seconds):
        raise RuntimeError("observer crash")

    profiling.add_listener(bad)
    profiling.add_listener(good)
    try:
        with profiling.phase("watched"):
            pass
    finally:
        profiling.remove_listener(bad)
        profiling.remove_listener(good)
    # the crashing listener neither killed the timed code nor starved
    # the healthy one
    assert [n for n, _ in seen] == ["watched"]
    assert seen[0][1] >= 0.0
    # removed listeners stop observing
    with profiling.phase("unwatched"):
        pass
    assert len(seen) == 1
    profiling.reset()


def test_concurrent_phases_do_not_corrupt_counts():
    profiling.reset()
    n_threads, n_iter = 4, 50
    barrier = threading.Barrier(n_threads)

    def worker():
        barrier.wait(timeout=5.0)
        for _ in range(n_iter):
            with profiling.phase("hot"):
                with profiling.phase("in"):
                    pass

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)

    cnt = profiling.counts()
    assert cnt["hot"] == n_threads * n_iter
    assert cnt["hot/in"] == n_threads * n_iter
    assert "in" not in cnt  # nesting never detached mid-flight
    profiling.reset()
