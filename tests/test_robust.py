"""Solve-health telemetry and fault-isolating sweep execution.

Fault injection happens through ``raft_tpu.sweep._CHUNK_EXEC_HOOK`` (the
dispatch seam): tests make one chunk raise or one design emit NaN
without constructing a pathological physics model, then assert the sweep
still completes, quarantines/flags exactly the right designs, and keeps
every status-ok row NaN-free.
"""

import numpy as np
import pytest

from raft_tpu import sweep as sweep_mod
from raft_tpu.designs import demo_spar
from raft_tpu.robust import (STATUS_NAN, STATUS_OK, STATUS_QUARANTINED,
                             SolveHealth, build_report, classify_health,
                             format_report, run_isolated)
from raft_tpu.robust.health import (STATUS_ILLCOND, STATUS_NONCONV,
                                    reduce_design_status, status_name)

AXES = [("platform.members.0.d",
         [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
          [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5]])]
STATES = [(4.0, 8.0), (6.0, 10.0)]


def _demo():
    return demo_spar(nw_freqs=(0.05, 0.4))


def _sweep(**kw):
    # n_iter=8: enough Borgman iterations that healthy demo designs
    # classify ok at the default resid_tol (at 6 the residual sits right
    # at 1e-3 and the telemetry honestly reports non-convergence)
    kw.setdefault("n_iter", 8)
    kw.setdefault("chunk_size", 2)
    return sweep_mod.sweep(_demo(), AXES, STATES, **kw)


@pytest.fixture
def chunk_hook():
    """Install a chunk-dispatch hook for the duration of one test."""
    def install(hook):
        sweep_mod._CHUNK_EXEC_HOOK = hook
    yield install
    sweep_mod._CHUNK_EXEC_HOOK = None


# ---------------------------------------------------------------------------
# host-side units: classification, isolation runner, report
# ---------------------------------------------------------------------------


def test_classify_health_severity_order():
    h = SolveHealth(
        resid=np.array([1e-6, 5e-2, 1e-6, np.nan, 5e-2]),
        cond=np.array([1e-2, 1e-2, 1e-14, 1e-2, 1e-14]),
        nonfinite=np.array([False, False, False, True, True]),
        n_fallback=np.zeros(5, np.int32))
    st = classify_health(h, resid_tol=1e-3, cond_tol=1e-10)
    assert st.dtype == np.int8
    assert st.tolist() == [STATUS_OK, STATUS_NONCONV, STATUS_ILLCOND,
                           STATUS_NAN, STATUS_NAN]
    # worst-over-cases reduction relies on the severity ordering
    assert reduce_design_status(st.reshape(1, 5)).tolist() == [STATUS_NAN]
    assert status_name(STATUS_QUARANTINED) == "quarantined"


def test_run_isolated_bisects_to_exact_poison():
    poison = {3, 5}
    calls = []

    def run(idx):
        calls.append(list(idx))
        if poison & set(int(i) for i in idx):
            raise RuntimeError("boom")
        return {"x": np.asarray(idx, dtype=float) * 10.0,
                "y": np.ones((len(idx), 2))}

    idx = np.arange(8)
    with pytest.warns(RuntimeWarning, match="quarantined"):
        merged, quarantined = run_isolated(run, idx, retries=1)
    assert quarantined.tolist() == [i in poison for i in range(8)]
    ok = ~quarantined
    np.testing.assert_array_equal(merged["x"][ok], idx[ok] * 10.0)
    assert np.isnan(merged["x"][quarantined]).all()
    assert merged["y"].shape == (8, 2)
    # the full chunk is retried exactly once before bisection starts
    assert calls[0] == calls[1] == list(range(8))


def test_run_isolated_all_poison_returns_none():
    def run(idx):
        raise ValueError("always")

    with pytest.warns(RuntimeWarning):
        merged, quarantined = run_isolated(run, np.arange(2), retries=0)
    assert merged is None
    assert quarantined.all()


def test_report_contents_and_format():
    status = np.array([0, 4, 3, 0], dtype=np.int8)
    combos = [(1.0,), (2.0,), (3.0,), (4.0,)]
    rep = build_report(status, combos=combos, axes=[("a.b", [1, 2, 3, 4])],
                       health={"resid": np.array([1e-5, np.nan, 2e-2, 1e-6]),
                               "cond": np.array([0.1, np.nan, 1e-13, 0.2])})
    assert rep["n_designs"] == 4 and not rep["all_ok"]
    assert rep["quarantined"] == [1]
    assert rep["failed"] == [1, 2]
    assert rep["counts"]["quarantined"] == 1 and rep["counts"]["nan"] == 1
    assert rep["failed_combos"][1] == {"a.b": 2.0}
    text = format_report(rep)
    assert "2/4 designs ok" in text and "design 1: quarantined" in text
    # an all-ok report is one line
    ok_rep = build_report(np.zeros(4, np.int8))
    assert format_report(ok_rep) == "sweep health: 4/4 designs ok"


# ---------------------------------------------------------------------------
# fault injection through the sweep chunk loop
# ---------------------------------------------------------------------------


def test_raising_chunk_quarantines_exact_design(chunk_hook):
    poison = 1

    def hook(idx, dispatch):
        if (np.asarray(idx) == poison).any():
            raise RuntimeError("injected chunk fault")
        return dispatch(idx)

    chunk_hook(hook)
    with pytest.warns(RuntimeWarning, match="isolating faults"):
        out = _sweep()

    status = out["status"]
    assert status.dtype == np.int8
    assert status[poison] == STATUS_QUARANTINED
    ok = status == STATUS_OK
    assert ok.tolist() == [i != poison for i in range(4)]
    # healthy designs all computed, quarantined row stays NaN
    assert np.isfinite(out["motion_std"][ok]).all()
    assert np.isnan(out["motion_std"][poison]).all()
    assert out["report"]["quarantined"] == [poison]
    assert not out["report"]["all_ok"]


def test_nan_design_flagged_not_ok(chunk_hook):
    nan_design = 2

    def hook(idx, dispatch):
        std, a_std, pr, hb = dispatch(idx)
        std = np.asarray(std).copy()
        std[np.asarray(idx) == nan_design] = np.nan
        return std, a_std, pr, hb

    chunk_hook(hook)
    out = _sweep()
    status = out["status"]
    assert status[nan_design] == STATUS_NAN
    ok = status == STATUS_OK
    assert ok.sum() == 3
    # acceptance: no status-ok entry contains NaN
    assert np.isfinite(out["motion_std"][ok]).all()
    assert np.isfinite(out["AxRNA_std"][ok]).all()
    assert out["report"]["counts"]["nan"] == 1


def test_checkpoint_resume_preserves_quarantine(tmp_path, chunk_hook):
    ckpt = str(tmp_path / "sweep.npz")
    poison = 1

    def hook(idx, dispatch):
        if (np.asarray(idx) == poison).any():
            raise RuntimeError("injected chunk fault")
        return dispatch(idx)

    chunk_hook(hook)
    with pytest.warns(RuntimeWarning):
        out1 = _sweep(checkpoint=ckpt)
    assert out1["status"][poison] == STATUS_QUARANTINED

    # resume: every design is done (computed or given up) -> no chunk
    # must execute, and the quarantine mark must survive the round trip
    def explode(idx, dispatch):
        raise AssertionError("resume must not re-execute chunks")

    chunk_hook(explode)
    out2 = _sweep(checkpoint=ckpt)
    np.testing.assert_array_equal(out2["status"], out1["status"])
    np.testing.assert_allclose(out2["motion_std"], out1["motion_std"])
    assert out2["report"]["quarantined"] == [poison]


def test_corrupt_checkpoint_warns_and_starts_fresh(tmp_path):
    ckpt = tmp_path / "sweep.npz"
    ckpt.write_bytes(b"this is not an npz archive")
    with pytest.warns(RuntimeWarning, match="unreadable"):
        out = _sweep(checkpoint=str(ckpt))
    assert (out["status"] == STATUS_OK).all()
    assert np.isfinite(out["motion_std"]).all()
    # and the sweep rewrote a valid checkpoint over the corpse
    with np.load(str(ckpt)) as dat:
        assert dat["done"].all() and "status" in dat


def test_old_schema_checkpoint_resumes_all_ok(tmp_path):
    ckpt = str(tmp_path / "sweep.npz")
    out1 = _sweep(checkpoint=ckpt)
    with np.load(ckpt) as dat:
        old = {k: dat[k] for k in dat.files
               if k not in ("status", "health_resid", "health_cond")}
    np.savez(ckpt, **old)  # pre-status schema

    out2 = _sweep(checkpoint=ckpt)
    # already-done designs from an old checkpoint are treated as ok
    assert (out2["status"] == STATUS_OK).all()
    assert out2["report"]["all_ok"]
    np.testing.assert_allclose(out2["motion_std"], out1["motion_std"])


def test_health_off_matches_and_skips_telemetry():
    out_on = _sweep()
    out_off = _sweep(health=False)
    np.testing.assert_allclose(out_off["motion_std"], out_on["motion_std"],
                               rtol=2e-5)
    # status still exists (finiteness-only classification), telemetry NaN
    assert (out_off["status"] == STATUS_OK).all()
    assert np.isnan(out_off["health"]["resid"]).all()
    assert np.isfinite(out_on["health"]["resid"]).all()
    assert np.isfinite(out_on["health"]["cond"]).all()


@pytest.mark.sentinel
def test_health_sweep_warm_run_no_recompile():
    """The health channel rides the existing executables: a repeat sweep
    (memoized programs) and the quarantine bisection (same padded chunk
    shape) must trigger zero XLA compiles."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    _sweep()  # warm: compiles + memoizes the chunk executables
    with RecompileSentinel() as s:
        snap = s.snapshot()
        out = _sweep()
        s.assert_no_recompile(snap, "warm health sweep")
    assert (out["status"] == STATUS_OK).all()

    poison = 3

    def hook(idx, dispatch):
        if (np.asarray(idx) == poison).any():
            raise RuntimeError("injected")
        return dispatch(idx)

    sweep_mod._CHUNK_EXEC_HOOK = hook
    try:
        with RecompileSentinel() as s:
            snap = s.snapshot()
            with pytest.warns(RuntimeWarning):
                out = _sweep()
            s.assert_no_recompile(snap, "bisecting sweep")
    finally:
        sweep_mod._CHUNK_EXEC_HOOK = None
    assert out["status"][poison] == STATUS_QUARANTINED

# ---------------------------------------------------------------------------
# classification edge cases + residual-trace units (flight recorder)
# ---------------------------------------------------------------------------


def test_classify_health_all_nan_residuals():
    """A solve whose residual channel is entirely NaN (e.g. the carry
    went non-finite on iteration 1) must classify NONCONV, not OK —
    ``resid > tol`` is False for NaN, so the non-finite check has to
    catch it explicitly."""
    h = SolveHealth(
        resid=np.full(3, np.nan),
        cond=np.full(3, 1e-2),
        nonfinite=np.zeros(3, bool),
        n_fallback=np.zeros(3, np.int32))
    st = classify_health(h, resid_tol=1e-3, cond_tol=1e-10)
    assert (st == STATUS_NONCONV).all()
    # NaN conditioning is likewise never trusted as well-conditioned
    h = SolveHealth(resid=np.array([1e-6]), cond=np.array([np.nan]),
                    nonfinite=np.array([False]),
                    n_fallback=np.zeros(1, np.int32))
    assert classify_health(h, 1e-3, 1e-10).tolist() == [STATUS_ILLCOND]


def test_classify_health_inf_first_iteration_carry():
    """The scan seeds its residual carry with +inf; a 0-progress solve
    reports that inf and must land NONCONV (inf > tol is True, but the
    finiteness guard must also hold on its own)."""
    h = SolveHealth(
        resid=np.array([np.inf]),
        cond=np.array([1e-2]),
        nonfinite=np.array([False]),
        n_fallback=np.zeros(1, np.int32))
    assert classify_health(h, 1e-3, 1e-10).tolist() == [STATUS_NONCONV]


def test_iterations_to_tolerance_units():
    from raft_tpu.robust import iterations_to_tolerance

    trace = np.array([
        [1.0, 1e-2, 1e-5, 1e-7],    # first hit at index 2 -> 1-based 3
        [1e-9, 1e-9, 1e-9, 1e-9],   # immediate -> 1
        [1.0, 0.5, 0.2, 0.1],       # never -> n_iter + 1 sentinel
        [1.0, np.nan, np.inf, 1e-9],  # non-finite lanes skipped
        [np.nan, np.nan, np.nan, np.nan],  # all non-finite -> sentinel
    ])
    out = iterations_to_tolerance(trace, 1e-4)
    assert out.dtype == np.int32
    assert out.tolist() == [3, 1, 5, 4, 5]
    # leading batch dims pass through
    assert iterations_to_tolerance(trace.reshape(5, 1, 4), 1e-4).shape \
        == (5, 1)


@pytest.mark.slow
def test_solver_resid_trace_contract():
    """Direct solver-level trace contract: ``resid_trace=True`` returns
    ``(Xi, health, trace[n_iter])`` with the trace in the solve's real
    dtype, the health residual equal to the trace's last entry, and the
    Xi/health outputs unchanged from the ``with_health`` solver."""
    import copy

    import jax.numpy as jnp

    from raft_tpu.core.model import Model
    from raft_tpu.parallel.case_solve import (design_params,
                                              make_parametric_solver)

    design = demo_spar(nw_freqs=(0.05, 0.4))
    model = Model(copy.deepcopy(design))
    fowt = model.fowtList[0]
    fowt.setPosition(np.zeros(6))
    fowt.calcStatics()
    fowt.calcHydroConstants()
    params, static = design_params(fowt, include_aero=False)

    n_iter = 5
    nw = static["nw"]
    zeta = jnp.ones((1, nw), dtype=jnp.complex128)
    beta = jnp.zeros(1)

    solve_t = make_parametric_solver(static, n_iter=n_iter,
                                     with_health=True, resid_trace=True)
    Xi_t, health_t, trace = solve_t(params, zeta, beta)
    solve_h = make_parametric_solver(static, n_iter=n_iter,
                                     with_health=True)
    Xi_h, health_h = solve_h(params, zeta, beta)

    assert trace.shape == (n_iter,)
    assert trace.dtype == np.asarray(params["w"]).dtype
    assert np.isfinite(np.asarray(trace)).all()
    np.testing.assert_array_equal(np.asarray(trace)[-1],
                                  np.asarray(health_t.resid))
    # the ys channel observes the scan; it never changes the solve
    np.testing.assert_array_equal(np.asarray(Xi_t), np.asarray(Xi_h))
    np.testing.assert_array_equal(np.asarray(health_t.resid),
                                  np.asarray(health_h.resid))
