"""Rotor aero (JAX BEM) parity tests vs the reference CCBlade goldens.

The reference's golden pickles were produced with the Fortran-backed
CCBlade (tests/test_rotor.py:83 in the reference, rtol=1e-5 against its
own binaries), one pickle per nacelle-yaw mode
(``IEA15MW_true_calcAero-yaw_mode{0..3}.pkl``).  Our BEM is an
independent implementation; agreement levels, documented per-channel
below, are:

- thrust T, torque Q, power, and the aero damping derivative dT/dU:
  1.5-4.8% (uniform offset; polar-spline / loss-model differences)
- cross-axis hub loads: the azimuthal-ASYMMETRY response (shear- and
  tilt-induced 1/rev load variation) is a consistent ~1.2x the Fortran
  goldens' across all operating points: My (shear-driven) +21..+25%,
  Mz (tilt-driven) +10..+25% in magnitude, with the uniform-response
  channels unaffected.  A round-5 forensic pass verified, term by term, that the
  inflow geometry (windComponents: shear height, tilt/yaw/azimuth
  trig, x/y/z_az), the azimuth->hub load rotation (all sign variants
  tested against the goldens), the trapezoid hub-load integration with
  zero endpoints, the Ning residual, and the 200-point AoA polar
  resample all match CCBlade's published formulation; n_sector and
  element-count refinement move My by <1%, and a Pitt-Peters skewed
  -wake correction at the 6 deg tilt is an order of magnitude too
  small to explain the gap.  A further experiment scaled the
  distributed loads by the combined Prandtl factor F: it zeroed the
  below-rated T/Q offset and brought My within 5%, but drove the
  dT/dU adjoint goldens from +3% to -8..-11% and above-rated T to
  +10% (F shrinks the negative-thrust tip elements there), so it is
  NOT CCBlade's convention — the evidence localizes the gap to the
  tip-region load distribution without identifying the mechanism.
  The residual factor therefore lives in the Fortran CCBlade's
  asymmetry response itself (not reproducible bit-for-bit without its
  source, which this environment lacks);
  ``test_cross_axis_response_bands`` locks the measured ratios PER
  YAW MODE — tightened to the measured +18..+27% window on the
  axisymmetric-rig mode 0, and at the documented +10..+30% window on
  the yawed-inflow modes 1-3, whose goldens exercise the
  heading-dependent asymmetry terms mode 0 never reaches — so any
  regression OR improvement is flagged.

The whole module degrades to SKIP (not error) when the reference
checkout's test-data tree is absent: the goldens are CCBlade artifacts
we cannot regenerate, not files this repo ships.
"""

import os
import pickle

import numpy as np
import pytest
import yaml

from raft_tpu.schema import get_from_dict
from raft_tpu.rotor.rotor import Rotor

TEST_DATA = "/root/reference/tests/test_data"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(TEST_DATA),
    reason=f"reference CCBlade golden data not present ({TEST_DATA})")

# measured cross-axis agreement bands per yaw mode (see module
# docstring): mode 0 is the fully-characterized axisymmetric rig; the
# yawed modes 1-3 carry the documented round-5 window until their
# asymmetry response is forensically tightened too
_MY_BANDS = {0: (1.18, 1.27), 1: (1.10, 1.30), 2: (1.10, 1.30),
             3: (1.10, 1.30)}
_MZ_SCALE = {0: 0.25, 1: 0.30, 2: 0.30, 3: 0.30}


def _build_rotor(yaw_mode=0):
    with open(f"{TEST_DATA}/IEA15MW.yaml") as f:
        design = yaml.load(f, Loader=yaml.FullLoader)
    t = design["turbine"]
    t["nrotors"] = 1
    t["yaw_mode"] = yaw_mode
    if isinstance(t.get("tower"), dict):
        t["tower"] = [t["tower"]]
    for k, d in [("rho_air", 1.225), ("mu_air", 1.81e-05), ("shearExp_air", 0.12),
                 ("rho_water", 1025.0), ("mu_water", 1.0e-03), ("shearExp_water", 0.12)]:
        t[k] = get_from_dict(design["site"], k, shape=0, default=d)
    s = design["settings"]
    w = np.arange(s.get("min_freq", 0.01),
                  s.get("max_freq", 1.0) + 0.5 * s.get("min_freq", 0.01),
                  s.get("min_freq", 0.01)) * 2 * np.pi
    rotor = Rotor(t, w, 0)
    rotor.setPosition()
    return rotor


def _load_gold(yaw_mode):
    path = f"{TEST_DATA}/IEA15MW_true_calcAero-yaw_mode{yaw_mode}.pkl"
    if not os.path.exists(path):
        pytest.skip(f"golden pickle for yaw_mode{yaw_mode} not shipped "
                    f"({path})")
    with open(path, "rb") as f:
        return pickle.load(f)


@pytest.fixture(scope="module")
def iea15mw_rotor():
    return _build_rotor(yaw_mode=0)


@pytest.fixture(scope="module")
def gold_mode0():
    return _load_gold(0)


def test_calcAero_thrust_torque_parity(iea15mw_rotor, gold_mode0):
    """T (f0[0]) and rotated torque/moment magnitudes vs CCBlade goldens."""
    rotor = iea15mw_rotor
    for entry in gold_mode0:
        c = entry["case"]
        if c["turbulence"] != 0 or c["wind_heading"] != 0:
            continue
        f0, f, a, b = rotor.calcAero(c)
        gf0 = entry["f_aero0"]
        # thrust
        assert abs(f0[0] - gf0[0]) / abs(gf0[0]) < 0.05, (c, f0[0], gf0[0])
        # torque slot (f0[4] mixes Q dominantly at small tilt)
        assert abs(f0[4] - gf0[4]) / abs(gf0[4]) < 0.05, (c, f0[4], gf0[4])
        # aero damping derivative dT/dU via b_aero[0,0]
        gb = entry["b_aero"][0, 0, 0]
        assert abs(b[0, 0, 0] - gb) / abs(gb) < 0.05, (c, b[0, 0, 0], gb)
        # signs of all six mean-load components must match
        big = np.abs(gf0) > 1e4  # skip near-zero channels
        assert np.all(np.sign(f0[big]) == np.sign(gf0[big])), (c, f0, gf0)


def test_calcAero_turbulent_excitation(iea15mw_rotor, gold_mode0):
    """Kaimal-spectrum wind excitation f_aero for turbulent cases."""
    rotor = iea15mw_rotor
    checked = 0
    for entry in gold_mode0:
        c = entry["case"]
        if c["turbulence"] == 0 or c["wind_heading"] != 0:
            continue
        f0, f, a, b = rotor.calcAero(c)
        gf = entry["f_aero"]
        # spectrum shape: correlation of |f| across frequencies near 1
        mine = np.abs(f[0, :])
        gold = np.abs(gf[0, :])
        if gold.max() > 0:
            num = np.dot(mine, gold) / (np.linalg.norm(mine) * np.linalg.norm(gold) + 1e-30)
            assert num > 0.9999, (c, num)
            # magnitude within BEM parity band
            assert abs(mine.max() - gold.max()) / gold.max() < 0.05
            checked += 1
    assert checked > 0


@pytest.mark.parametrize("yaw_mode", [0, 1, 2, 3])
def test_cross_axis_response_bands(yaw_mode):
    """Regression-lock the cross-axis hub-load ratios vs the CCBlade
    goldens, decomposed in the rotor (CC) frame, per nacelle-yaw mode.

    The golden ``f_aero0`` is ``R_q @ [T,Y,Z]`` / ``R_q @ [My,Q,Mz]``
    (the reference's moments_axis ordering, raft_rotor.py:841-847), so
    applying ``R_q.T`` recovers CCBlade's own hub-frame channels.  The
    bands encode the measured agreement (module docstring): mode 0's
    are tightened to the characterized +18..+27% My window; modes 1-3
    (``yaw_mode1-3`` goldens: heading-following / commanded-yaw
    inflow) hold the documented +10..+30% window.  R_q is re-read per
    case because yawed modes rotate the shaft frame with the case
    heading.  Tighten further when the asymmetry-response gap closes.
    """
    rotor = _build_rotor(yaw_mode=yaw_mode)
    gold = _load_gold(yaw_mode)
    my_lo, my_hi = _MY_BANDS[yaw_mode]
    checked = 0
    for entry in gold:
        c = entry["case"]
        if c["turbulence"] != 0:
            continue
        if yaw_mode == 0 and c.get("wind_heading", 0) != 0:
            continue
        f0, _, _, _ = rotor.calcAero(c)
        Rq = np.asarray(rotor.R_q)  # per-case: setYaw ran inside calcAero
        F_cc = Rq.T @ np.asarray(f0[:3])
        M_cc = Rq.T @ np.asarray(f0[3:])
        gF = Rq.T @ entry["f_aero0"][:3]
        gM = Rq.T @ entry["f_aero0"][3:]
        T, My, Q, Mz = F_cc[0], M_cc[0], M_cc[1], M_cc[2]
        gT, gMy, gQ, gMz = gF[0], gM[0], gM[1], gM[2]
        # uniform-response channels: tight on every yaw mode
        assert abs(T / gT - 1.0) < 0.05, (yaw_mode, c, T, gT)
        assert abs(Q / gQ - 1.0) < 0.05, (yaw_mode, c, Q, gQ)
        # asymmetry-response channels: locked at the measured ratios
        assert my_lo < My / gMy < my_hi, (yaw_mode, c, My, gMy)
        # Mz crosses zero near rated wind speed, so a ratio band is
        # ill-posed; bound its error by the dominant cross-axis scale
        assert abs(Mz - gMz) < _MZ_SCALE[yaw_mode] * abs(gMy), \
            (yaw_mode, c, Mz, gMz, gMy)
        checked += 1
    assert checked >= 6


def test_derivatives_flow_through_solver(iea15mw_rotor):
    """dT/dU must be nonzero and smooth (implicit-diff through the BEM
    root solve; naive AD through bisection returns ~0)."""
    from raft_tpu.rotor import bem as B

    rotor = iea15mw_rotor
    U = 8.0
    Om = np.interp(U, rotor.Uhub, rotor.Omega_rpm) * 2 * np.pi / 60
    pitch = np.radians(np.interp(U, rotor.Uhub, rotor.pitch_deg))
    out, derivs = B.evaluate_with_derivatives(rotor.bem, U, Om, pitch)
    assert float(derivs["dT_dU"]) > 1e4
    # finite-difference cross-check at 0.1% step
    o1 = B.evaluate(rotor.bem, U + 0.01, Om, pitch)
    o0 = B.evaluate(rotor.bem, U - 0.01, Om, pitch)
    fd = (float(o1["T"]) - float(o0["T"])) / 0.02
    assert abs(float(derivs["dT_dU"]) - fd) / abs(fd) < 1e-3


def test_power_positive_below_rated(iea15mw_rotor):
    from raft_tpu.rotor import bem as B

    rotor = iea15mw_rotor
    for U in (6.0, 9.0, 11.0):
        Om = np.interp(U, rotor.Uhub, rotor.Omega_rpm) * 2 * np.pi / 60
        pitch = np.radians(np.interp(U, rotor.Uhub, rotor.pitch_deg))
        out = B.evaluate(rotor.bem, U, Om, pitch)
        assert float(out["P"]) > 0
        assert float(out["T"]) > 0
        assert 0 < float(out["CP"]) < 0.6  # Betz-ish sanity
