"""Sweep-as-a-service: coalescing, robustness contract, concurrency.

The solve server's contract is the sweep's, lifted to many tenants:
coalescing changes THROUGHPUT, never results.  Every request's slice of
a shared round must be bit-identical to a solo ``sweep()`` at the same
chunk extent, with zero real XLA compiles once the server is warm —
and every fault (cancellation, deadline, poison design, preempt drill)
fails only the targeted request while cohabiting requests deliver.

The cheap admission/scheduling/breaker tests drive the server's
internals directly (no worker thread, no JAX dispatch); the end-to-end
tests share one module-scoped warmed server.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu import sweep as sweep_mod
from raft_tpu.designs import demo_spar
from raft_tpu.obs import ledger as obs_ledger
from raft_tpu.obs import live
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.robust import STATUS_OK
from raft_tpu.robust import chaos as chaos_mod
from raft_tpu.robust import elastic
from raft_tpu.robust.quarantine import CircuitBreaker
from raft_tpu.serve import (DeadlineExceeded, RequestCancelled,
                            RequestRejected, ServerSaturated, SolveServer,
                            point_fingerprint)
from raft_tpu.sweep import sweep

V = [[9.4, 9.4, 6.5, 6.5], [10.0, 10.0, 6.5, 6.5],
     [10.5, 10.5, 6.5, 6.5], [11.0, 11.0, 6.5, 6.5],
     [9.0, 9.0, 6.5, 6.5], [9.6, 9.6, 6.5, 6.5],
     [10.2, 10.2, 6.5, 6.5], [10.8, 10.8, 6.5, 6.5]]
AXES = [("platform.members.0.d", V)]
STATES = [(4.0, 8.0), (6.0, 10.0)]
N_ITER = 8

RESULT_KEYS = ("motion_std", "AxRNA_std", "mass", "displacement", "GMT",
               "status")


def _pt(i):
    return (V[i],)


def _mini_server(**cfg):
    """A server that is never started: admission / composition units."""
    base = {"chunk_size": 2, "max_round_designs": 8,
            "max_pending_designs": 64, "max_request_designs": 4,
            "retry_rounds": 0}
    base.update(cfg)
    chaos = base.pop("chaos", False)
    return SolveServer(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES,
                       n_iter=N_ITER, config=base, chaos=chaos)


def _assert_rows_identical(direct, result):
    for k in RESULT_KEYS:
        x, y = np.asarray(direct[k]), np.asarray(result[k])
        assert x.dtype == y.dtype, (k, x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y, err_msg=k)
    for k in direct["health"]:
        np.testing.assert_array_equal(
            np.asarray(direct["health"][k]),
            np.asarray(result["health"][k]), err_msg=f"health.{k}")


# ---------------------------------------------------------------------------
# admission control / backpressure (no worker, no dispatch)
# ---------------------------------------------------------------------------


def test_admission_saturation_and_typed_rejects():
    srv = _mini_server(max_pending_designs=3)
    t1 = srv.submit([_pt(0), _pt(1)])
    assert not t1.done
    with pytest.raises(ServerSaturated) as ei:
        srv.submit([_pt(2), _pt(3)])
    assert ei.value.reason == "saturated" and ei.value.http_status == 429
    # one more design still fits the bound exactly
    srv.submit([_pt(2)])
    with pytest.raises(RequestRejected) as ei:
        srv.submit([_pt(0)] * 5)                  # > max_request_designs
    assert ei.value.reason == "too_large"
    with pytest.raises(RequestRejected) as ei:
        srv.submit([])
    assert ei.value.reason == "too_large"
    with pytest.raises(RequestRejected) as ei:
        srv.submit([_pt(0)], deadline_s=-1.0)
    assert ei.value.reason == "deadline"
    with pytest.raises(RequestRejected):
        srv.submit([(V[0], V[1])])                # wrong arity for 1 axis
    assert srv.stats()["rejected"] == 4
    srv.close()
    with pytest.raises(RequestRejected) as ei:
        t1.result(timeout=1)
    assert ei.value.reason == "closed"
    with pytest.raises(RequestRejected) as ei:
        srv.submit([_pt(0)])
    assert ei.value.reason == "closed"


def test_deadline_expires_before_dispatch_and_cancel_masks_rows():
    srv = _mini_server()
    doomed = srv.submit([_pt(0)], deadline_s=0.01)
    alive = srv.submit([_pt(1)])
    victim = srv.submit([_pt(2)])
    assert victim.cancel() is True
    assert victim.cancel() is False               # already delivered
    with pytest.raises(RequestCancelled):
        victim.result(timeout=1)
    time.sleep(0.05)
    members = srv._compose_round()
    assert [r.id for r in members] == [alive.id]  # masked + expired dropped
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=1)
    st = srv.stats()
    assert st["cancelled"] == 1 and st["deadline"] == 1
    srv.close(drain=False)


def test_priority_classes_and_tenant_fairness():
    srv = _mini_server(max_round_designs=4, max_pending_designs=64)
    a1 = srv.submit([_pt(0)], tenant="a")
    a2 = srv.submit([_pt(1)], tenant="a")
    a3 = srv.submit([_pt(2)], tenant="a")
    b1 = srv.submit([_pt(3)], tenant="b")
    c1 = srv.submit([_pt(4)], tenant="c", priority=0)
    members = srv._compose_round()
    ids = [r.id for r in members]
    # priority 0 schedules first; inside priority 1 the round-robin
    # takes one request per tenant per cycle — tenant a cannot fill the
    # round before b gets a slot
    assert ids[0] == c1.id
    assert ids[1] in (a1.id, b1.id) and ids[2] in (a1.id, b1.id)
    assert ids[3] == a2.id                        # second rr cycle
    assert len(ids) == 4                          # a3 left for next round
    assert srv.stats()["queued"] == 1
    srv._requeue(members)
    assert srv.stats()["queued"] == 5
    srv.close(drain=False)
    for t in (a1, a2, a3, b1, c1):
        with pytest.raises(RequestRejected):
            t.result(timeout=1)


def test_drain_checkpoint_and_resume(tmp_path):
    path = str(tmp_path / "drain.json")
    srv = _mini_server(drain_path=path)
    srv.submit([_pt(0), _pt(1)], tenant="x", priority=2, deadline_s=30.0)
    srv.submit([_pt(2)], tenant="y")
    srv.close()
    spec = json.load(open(path))
    assert [r["tenant"] for r in spec["requests"]] == ["x", "y"]
    assert spec["requests"][0]["priority"] == 2
    assert spec["requests"][0]["deadline_s"] == 30.0

    srv2 = _mini_server(drain_path=path)
    assert srv2.resume_pending() == 2
    assert srv2.stats()["queued"] == 2
    srv2.close(drain=False)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


def test_circuit_breaker_trip_halfopen_reset():
    now = [0.0]
    br = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=lambda: now[0])
    fp = "design-fp"
    assert br.allows(fp)
    assert br.record_failure(fp) is False         # below threshold
    assert br.allows(fp)
    assert br.record_failure(fp) is True          # trips
    assert not br.allows(fp) and br.tripped() == [fp]
    now[0] = 5.0
    assert not br.allows(fp)                      # still cooling
    now[0] = 10.0
    assert br.allows(fp)                          # half-open probe
    # the probe failing re-trips immediately (failure count retained)
    assert br.record_failure(fp) is True
    assert not br.allows(fp)
    now[0] = 20.0
    assert br.allows(fp)
    br.record_success(fp)
    assert br.allows(fp) and br.tripped() == []
    assert br.record_failure(fp) is False         # history forgotten


def test_breaker_fast_fails_admission():
    srv = _mini_server(breaker_threshold=1)
    fp = point_fingerprint(_pt(0))
    srv._breaker.record_failure(fp)
    with pytest.raises(RequestRejected) as ei:
        srv.submit([_pt(0)])
    assert ei.value.reason == "breaker"
    srv.submit([_pt(1)])                          # other designs unaffected
    srv.close(drain=False)


def test_point_fingerprint_stability():
    assert point_fingerprint(_pt(0)) == point_fingerprint(_pt(0))
    assert point_fingerprint(_pt(0)) != point_fingerprint(_pt(1))


# ---------------------------------------------------------------------------
# request-layer chaos seams
# ---------------------------------------------------------------------------


def test_cancel_storm_seam():
    srv = _mini_server(chaos="cancel_storm:count=2")
    t1 = srv.submit([_pt(0)])
    t2 = srv.submit([_pt(1)])
    t3 = srv.submit([_pt(2)])
    members = srv._compose_round()
    assert len(members) == 1                      # two victims cancelled
    cancelled = [t for t in (t1, t2, t3) if t.done]
    assert len(cancelled) == 2
    for t in cancelled:
        with pytest.raises(RequestCancelled):
            t.result(timeout=1)
    srv.close(drain=False)


def test_req_flood_seam_drives_admission():
    srv = _mini_server(chaos="req_flood:count=6", max_pending_designs=4,
                       max_request_designs=1)
    real = srv.submit([_pt(0)])
    members = srv._compose_round()
    # the flood's synthetics are cancelled post-admission; the real
    # request still dispatches, and overflow shed through the 429 path
    assert [r.id for r in members] == [real.id]
    st = srv.stats()
    assert st["rejected"] >= 3                    # 4-design bound, 1 used
    assert st["cancelled"] >= 1
    srv.close(drain=False)


def test_slow_client_delays_only_its_delivery():
    srv = _mini_server(chaos="slow_client:secs=0.3")
    t = srv.submit([_pt(0)])
    req = srv._pending[0]
    srv._deliver_result(req, {"grid": [_pt(0)]})
    assert not t.done                             # delivery stalled
    assert t.result(timeout=2)["grid"] == [_pt(0)]
    srv.close(drain=False)


def test_preempt_hook_routing_unit():
    calls = []
    hook = lambda: calls.append(1) or True  # noqa: E731
    chaos_mod.register_preempt_hook(hook)
    try:
        plan = chaos_mod.ChaosPlan("preempt:p=1")
        assert plan.maybe_preempt(0) is True      # routed, no SIGTERM
        assert calls == [1]
    finally:
        chaos_mod.unregister_preempt_hook(hook)
    assert chaos_mod.preempt_hook() is None
    # unregistering someone else's hook must not unhook the current one
    chaos_mod.register_preempt_hook(hook)
    chaos_mod.unregister_preempt_hook(lambda: False)
    assert chaos_mod.preempt_hook() is hook
    chaos_mod.unregister_preempt_hook()


# ---------------------------------------------------------------------------
# size buckets
# ---------------------------------------------------------------------------


def test_round_bucket_padding():
    srv = _mini_server(chunk_size=2, max_round_designs=8)
    assert [srv._bucket(n) for n in (1, 2, 3, 4, 5, 8)] == [2, 2, 4, 4, 8, 8]
    padded = srv._warm_pad([_pt(0), _pt(1), _pt(2)])
    assert len(padded) == 4 and padded[3] == _pt(0)
    assert srv._warm_pad([_pt(1)]) == [_pt(1), _pt(1)]
    srv.close(drain=False)


# ---------------------------------------------------------------------------
# multi-run /status + aggregated /healthz (live endpoint)
# ---------------------------------------------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_status_lists_concurrent_runs_and_healthz_aggregates(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_METRICS_PORT", "0")
    live.stop_server()
    obs_metrics.reset()
    r1 = obs_ledger.start_run("sweep")
    r2 = obs_ledger.start_run("serve")
    try:
        srv = live.ensure_server()
        assert srv is not None
        code, body = _get(srv.url + "/status")
        assert code == 200
        ids = [run["run_id"] for run in body["runs"]]
        assert ids == [r1.run_id, r2.run_id]
        assert body["active"]["run_id"] == r2.run_id   # most recent

        # watchdog-overdue aggregates across runs: EITHER being overdue
        # is 503, and the payload names the offenders
        elastic._set_overdue(True, key=r1.run_id)
        elastic._set_overdue(True, key=r2.run_id)
        code, body = _get(srv.url + "/healthz")
        assert code == 503
        assert body["overdue_runs"] == sorted([r1.run_id, r2.run_id])
        elastic._set_overdue(False, key=r2.run_id)
        code, body = _get(srv.url + "/healthz")
        assert code == 503 and body["overdue_runs"] == [r1.run_id]
        elastic._set_overdue(False, key=r1.run_id)
        code, body = _get(srv.url + "/healthz")
        assert code == 200 and body["ok"] is True

        r1.finish(ok=True)
        code, body = _get(srv.url + "/status")
        assert [run["run_id"] for run in body["runs"]] == [r2.run_id]
    finally:
        elastic._OVERDUE.clear()
        r1.close()
        r2.close()
        live.stop_server()
        obs_metrics.reset()


# ---------------------------------------------------------------------------
# end-to-end: one warmed module server
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    mp = pytest.MonkeyPatch()
    ldir = tmp_path_factory.mktemp("serve-ledger")
    drain = str(tmp_path_factory.mktemp("serve-drain") / "drain.json")
    mp.setenv("RAFT_TPU_LEDGER", str(ldir))
    srv = SolveServer(
        demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES, n_iter=N_ITER,
        config={"chunk_size": 2, "max_round_designs": 8,
                "max_pending_designs": 64, "max_request_designs": 6,
                "retry_rounds": 0, "drain_path": drain})
    srv.start(warm="buckets")
    try:
        yield srv, ldir
    finally:
        srv.close()
        mp.undo()


def _serve_events(ldir):
    paths = [p for p in obs_ledger.list_runs(str(ldir))
             if "-serve-" in p]
    assert len(paths) == 1, paths
    return obs_ledger.read_events(paths[0])


@pytest.mark.sentinel
@pytest.mark.slow
def test_coalesced_rounds_bit_identical_zero_compiles(served):
    from raft_tpu.analysis.recompile import RecompileSentinel

    srv, ldir = served
    reqs = [[_pt(0), _pt(1)], [_pt(2)], [_pt(3), _pt(4), _pt(5)],
            [_pt(6)], [_pt(7), _pt(0)], [_pt(1), _pt(2)]]
    with RecompileSentinel() as s:
        snap = s.snapshot()
        tickets = [srv.submit(pts, tenant=f"t{i % 3}")
                   for i, pts in enumerate(reqs)]
        results = [t.result(timeout=300) for t in tickets]
        s.assert_no_recompile(snap, "warmed serve rounds")

    st = srv.stats()
    assert st["completed"] >= len(reqs)
    # coalescing: sub-second submission against multi-second rounds —
    # strictly fewer rounds than requests, and the ledger agrees
    assert st["rounds"] < st["accepted"]
    rounds = [e for e in _serve_events(ldir) if e["event"] == "serve_round"]
    assert sum(e["requests"] for e in rounds) >= len(reqs)
    assert any(e["requests"] > 1 for e in rounds)

    for pts, res in zip(reqs, results):
        assert list(res["grid"]) == pts
        assert (np.asarray(res["status"]) == STATUS_OK).all()
    # bit-identity against solo sweeps at the served chunk extent
    for idx in (0, 2):
        direct = sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES,
                       n_iter=N_ITER, chunk_size=2, grid=reqs[idx])
        _assert_rows_identical(direct, results[idx])


@pytest.mark.slow
def test_preempt_drill_keeps_resident_server_alive(served):
    srv, ldir = served
    drains_before = srv.stats()["drains"]
    srv.inject_chaos("preempt:chunk=0")
    res = srv.solve([_pt(3), _pt(4)], timeout=300)
    # the preempt fired mid-round, was routed through the drain hook,
    # and the round still delivered — the process is, demonstrably, us
    assert (np.asarray(res["status"]) == STATUS_OK).all()
    assert srv.stats()["drains"] == drains_before + 1
    pre = [e for e in _serve_events(ldir) if e["event"] == "preempt"]
    assert pre and pre[-1]["signal"] == "drill" and pre[-1]["resident"]
    assert pre[-1]["checkpoint"] == srv.cfg["drain_path"]
    assert chaos_mod.preempt_hook() is not None   # still registered


@pytest.mark.slow
def test_request_done_and_round_events_in_ledger(served):
    srv, ldir = served
    events = _serve_events(ldir)
    done = [e for e in events if e["event"] == "request_done"]
    assert done and all(e["ok"] for e in done)
    accepts = [e for e in events if e["event"] == "request_accept"]
    assert {e["tenant"] for e in accepts} >= {"t0", "t1", "t2"}


# ---------------------------------------------------------------------------
# concurrent sweep() entry: the refactor the server rides on
# ---------------------------------------------------------------------------


@pytest.mark.sentinel
@pytest.mark.slow
def test_concurrent_sweeps_share_memo_bit_identical(served):
    """Two threads entering a WARM ``sweep()`` with overlapping design
    batches: no memo/exec-cache corruption, zero extra compiles, and
    results bit-identical to the sequential runs."""
    from raft_tpu.analysis.recompile import RecompileSentinel

    base = demo_spar(nw_freqs=(0.05, 0.4))
    kw = dict(n_iter=N_ITER, chunk_size=2)
    grid_a = [_pt(0), _pt(1), _pt(2), _pt(3)]
    grid_b = [_pt(2), _pt(3), _pt(4), _pt(5)]     # overlaps grid_a
    seq_a = sweep(base, AXES, STATES, grid=grid_a, **kw)
    seq_b = sweep(base, AXES, STATES, grid=grid_b, **kw)

    memo_keys = set(sweep_mod._TEMPLATE_MEMO)
    results = {}
    errors = []

    def _worker(name, grid):
        try:
            results[name] = sweep(base, AXES, STATES, grid=grid, **kw)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append((name, e))

    with RecompileSentinel() as s:
        snap = s.snapshot()
        threads = [threading.Thread(target=_worker, args=("a", grid_a)),
                   threading.Thread(target=_worker, args=("b", grid_b))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        s.assert_no_recompile(snap, "concurrent warm sweeps")

    _assert_rows_identical(seq_a, results["a"])
    _assert_rows_identical(seq_b, results["b"])
    assert set(sweep_mod._TEMPLATE_MEMO) == memo_keys  # no memo churn


# ---------------------------------------------------------------------------
# HTTP front
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_http_front_solve_result_cancel_stats(served):
    from raft_tpu.serve.http import ServeFront

    srv, _ = served
    front = ServeFront(srv, host="127.0.0.1", port=0)
    try:
        def _post(path, payload=None):
            req = urllib.request.Request(
                front.url + path, method="POST",
                data=json.dumps(payload or {}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status, json.loads(resp.read().decode())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read().decode())

        code, body = _post("/solve", {"points": [_pt(0), _pt(1)],
                                      "tenant": "http"})
        assert code == 202
        rid = body["request_id"]
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            code, body = _get(front.url + f"/result/{rid}")
            if code != 202:
                break
            time.sleep(0.25)
        assert code == 200 and body["status"] == "done"
        rows = np.asarray(body["result"]["mass"])
        direct = sweep(demo_spar(nw_freqs=(0.05, 0.4)), AXES, STATES,
                       n_iter=N_ITER, chunk_size=2, grid=[_pt(0), _pt(1)])
        np.testing.assert_allclose(rows, np.asarray(direct["mass"]))

        code, body = _post("/solve", {"points": [_pt(0)] * 99})
        assert code == 400 and body["reason"] == "too_large"
        code, body = _get(front.url + "/result/req-999999")
        assert code == 404
        code, body = _get(front.url + "/stats")
        assert code == 200 and body["completed"] >= 1
        code, body = _get(front.url + "/healthz")
        assert code == 200
    finally:
        front.close()
