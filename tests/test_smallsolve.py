"""Batch-last tiny complex solves (the hot impedance-solve kernel)."""

import numpy as np
import jax.numpy as jnp
import pytest

from raft_tpu.parallel import smallsolve


def _random_systems(rng, B, n=6, m=1, cond="good"):
    Z = rng.normal(size=(B, n, n)) + 1j * rng.normal(size=(B, n, n))
    if cond == "good":
        Z = Z + 8.0 * np.eye(n)
    elif cond == "pivoty":
        # zero leading diagonal entries so elimination *requires* pivoting
        Z[:, 0, 0] = 0.0
        Z[:, 2, 2] = 0.0
    F = rng.normal(size=(B, n, m)) + 1j * rng.normal(size=(B, n, m))
    return Z, F


@pytest.mark.parametrize("cond", ["good", "pivoty"])
def test_jnp_solver_matches_linalg(cond):
    rng = np.random.default_rng(0)
    Z, F = _random_systems(rng, 257, cond=cond)
    ref = np.linalg.solve(Z, F)

    Zt = jnp.asarray(Z.transpose(1, 2, 0))
    Ft = jnp.asarray(F.transpose(1, 2, 0))
    xr, xi = smallsolve.solve_batchlast_jnp(jnp.real(Zt), jnp.imag(Zt),
                                            jnp.real(Ft), jnp.imag(Ft))
    got = (np.asarray(xr) + 1j * np.asarray(xi)).transpose(2, 0, 1)
    np.testing.assert_allclose(got, ref, rtol=1e-9, atol=1e-9)


def test_pallas_interpret_matches_jnp():
    rng = np.random.default_rng(1)
    Z, F = _random_systems(rng, 130, m=3)
    Zt = jnp.asarray(Z.transpose(1, 2, 0))
    Ft = jnp.asarray(F.transpose(1, 2, 0))
    args = (jnp.real(Zt), jnp.imag(Zt), jnp.real(Ft), jnp.imag(Ft))
    xr0, xi0 = smallsolve.solve_batchlast_jnp(*args)
    xr1, xi1 = smallsolve.solve_batchlast_pallas(*args, interpret=True)
    np.testing.assert_allclose(np.asarray(xr1), np.asarray(xr0), atol=1e-10)
    np.testing.assert_allclose(np.asarray(xi1), np.asarray(xi0), atol=1e-10)


def test_cond_tracking_near_singular():
    """Conditioning signal: healthy pivots ~O(1) ratio, a near-singular
    yaw row (zero-stiffness mooring at ~1e-7 scale) drives the ratio to
    ~1e-7 while the solution still matches jnp.linalg.solve within the
    accuracy the conditioning allows — both jnp and Pallas (interpret)
    elimination paths, since both record the same pivot magnitudes."""
    rng = np.random.default_rng(7)
    B = 64
    Z, F = _random_systems(rng, B, m=2)
    # scale the yaw row/column of half the batch down to ~1e-7: the
    # pivot-magnitude ratio collapses but the matrix stays invertible
    scale = 1e-7
    Z[::2, 5, :] *= scale
    Z[::2, :, 5] *= scale
    ref = np.linalg.solve(Z, F)

    Zt = jnp.asarray(Z.transpose(1, 2, 0))
    Ft = jnp.asarray(F.transpose(1, 2, 0))
    args = (jnp.real(Zt), jnp.imag(Zt), jnp.real(Ft), jnp.imag(Ft))

    xr, xi, cond = smallsolve.solve_batchlast_jnp_cond(*args)
    got = (np.asarray(xr) + 1j * np.asarray(xi)).transpose(2, 0, 1)
    # the sick systems lose ~7 digits by construction; compare against
    # the dense reference at a tolerance the conditioning supports
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    cond = np.asarray(cond)
    assert cond.shape == (B,)
    assert np.all(cond[::2] < 1e-5), "near-singular lanes must flag"
    assert np.all(cond[1::2] > 1e-3), "healthy lanes must not flag"
    # the flag separates the two populations by orders of magnitude
    assert cond[::2].max() < 1e-2 * cond[1::2].min()

    xr2, xi2, cond2 = smallsolve.solve_batchlast_pallas(
        *args, interpret=True, with_cond=True)
    got2 = (np.asarray(xr2) + 1j * np.asarray(xi2)).transpose(2, 0, 1)
    np.testing.assert_allclose(got2, got, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(cond2), cond, rtol=1e-5)

    # with_cond=False stays the seed-identical two-output signature
    xr3, xi3 = smallsolve.solve_batchlast_pallas(*args, interpret=True)
    np.testing.assert_allclose(np.asarray(xr3), np.asarray(xr),
                               rtol=1e-6, atol=1e-8)


def test_impedance_multi_cond_matches_multi():
    rng = np.random.default_rng(8)
    nw, nH = 24, 2
    Z, _ = _random_systems(rng, nw)
    Fh = rng.normal(size=(nH, 6, nw)) + 1j * rng.normal(size=(nH, 6, nw))

    base = np.asarray(smallsolve.solve_impedance_multi(jnp.asarray(Z), jnp.asarray(Fh)))
    xh, cond = smallsolve.solve_impedance_multi_cond(jnp.asarray(Z), jnp.asarray(Fh))
    np.testing.assert_allclose(np.asarray(xh), base, rtol=1e-12, atol=1e-12)
    cond = np.asarray(cond)
    assert cond.shape == (nw,)
    assert np.all((cond > 0) & (cond <= 1.0))


def test_impedance_wrappers():
    rng = np.random.default_rng(2)
    nw, nH = 40, 3
    Z, _ = _random_systems(rng, nw)
    F = rng.normal(size=(6, nw)) + 1j * rng.normal(size=(6, nw))
    Fh = rng.normal(size=(nH, 6, nw)) + 1j * rng.normal(size=(nH, 6, nw))

    x = np.asarray(smallsolve.solve_impedance(jnp.asarray(Z), jnp.asarray(F)))
    ref = np.stack([np.linalg.solve(Z[i], F[:, i]) for i in range(nw)], axis=1)
    np.testing.assert_allclose(x, ref, rtol=1e-9, atol=1e-9)

    xh = np.asarray(smallsolve.solve_impedance_multi(jnp.asarray(Z), jnp.asarray(Fh)))
    for h in range(nH):
        ref_h = np.stack([np.linalg.solve(Z[i], Fh[h, :, i]) for i in range(nw)], axis=1)
        np.testing.assert_allclose(xh[h], ref_h, rtol=1e-9, atol=1e-9)

    Zinv = np.asarray(smallsolve.inverse_impedance(jnp.asarray(Z)))
    np.testing.assert_allclose(Zinv, np.linalg.inv(Z), rtol=1e-9, atol=1e-9)


# ---------------------------------------------------------------------------
# path selection + autotune (RAFT_TPU_SMALLSOLVE)
# ---------------------------------------------------------------------------


@pytest.fixture
def tune_cache(monkeypatch):
    """A fresh autotune cache for the duration of one test."""
    cache: dict = {}
    monkeypatch.setattr(smallsolve, "_TUNE_CACHE", cache)
    return cache


def test_mode_override_parity(monkeypatch, tune_cache):
    """All three RAFT_TPU_SMALLSOLVE modes produce the same solution
    (the forced Pallas path runs in interpret mode off-TPU)."""
    rng = np.random.default_rng(3)
    nw, nH = 32, 2
    Z, _ = _random_systems(rng, nw)
    Fh = rng.normal(size=(nH, 6, nw)) + 1j * rng.normal(size=(nH, 6, nw))
    outs = {}
    for mode in ("auto", "jnp", "pallas"):
        monkeypatch.setenv("RAFT_TPU_SMALLSOLVE", mode)
        outs[mode] = np.asarray(smallsolve.solve_impedance_multi(
            jnp.asarray(Z), jnp.asarray(Fh)))
    np.testing.assert_array_equal(outs["auto"], outs["jnp"])
    # identical arithmetic, different execution engine: tight tolerance
    np.testing.assert_allclose(outs["pallas"], outs["jnp"],
                               rtol=1e-12, atol=1e-12)


def test_mode_validation(monkeypatch):
    from raft_tpu.config import smallsolve_mode

    monkeypatch.setenv("RAFT_TPU_SMALLSOLVE", "PALLAS")  # case-folded
    assert smallsolve_mode() == "pallas"
    monkeypatch.setenv("RAFT_TPU_SMALLSOLVE", "maybe")
    with pytest.raises(ValueError, match="RAFT_TPU_SMALLSOLVE"):
        smallsolve_mode()


def test_auto_mode_off_tpu_is_jnp_without_benchmark(tune_cache, monkeypatch):
    """'auto' off-TPU short-circuits to jnp: no benchmark runs (the CPU
    test suite must not pay candidate compiles under the sentinel)."""
    monkeypatch.setenv("RAFT_TPU_SMALLSOLVE", "auto")
    kind, block, interpret = smallsolve._solver_choice(6, 1, 200)
    assert (kind, block, interpret) == ("jnp", None, False)
    assert tune_cache == {}  # nothing benchmarked, nothing cached
    assert smallsolve.use_pallas(6, 1, 200) is False
    assert smallsolve.use_pallas() is False  # legacy no-arg semantics


def test_autotune_caches_pallas_winner(tune_cache):
    """Fake benchmark where a Pallas block wins: the winner (path AND
    block) is cached and served without re-benchmarking."""
    calls = []

    def bench(kind, block):
        calls.append((kind, block))
        if kind == "jnp":
            return 10.0
        return {256: 5.0, 512: 2.0}[block]  # 512 is fastest

    entry = smallsolve.autotune(6, 1, 700, backend="faketpu", bench=bench,
                                candidates=[256, 512])
    assert entry["choice"] == "pallas" and entry["block"] == 512
    assert entry["times"]["jnp"] == 10.0
    # cache hit: same key never benchmarks again
    n_calls = len(calls)
    again = smallsolve.autotune(6, 1, 700, backend="faketpu", bench=bench)
    assert again is entry and len(calls) == n_calls
    rep = smallsolve.tuning_report()
    assert rep["n6_m1_B700_faketpu"]["choice"] == "pallas"


def test_autotune_caches_jnp_winner_and_failures(tune_cache):
    """The BENCH_r05 regression case: when jnp times faster the tuner
    must select it (caching 'jnp wins' is the whole point), and a
    candidate that fails to compile is recorded, not fatal."""
    def bench(kind, block):
        if kind == "jnp":
            return 1.0
        if block == 256:
            raise RuntimeError("mosaic VMEM overflow")
        return 2.0  # pallas slower

    entry = smallsolve.autotune(6, 1, 700, backend="faketpu", bench=bench,
                                candidates=[256, 512])
    assert entry["choice"] == "jnp" and entry["block"] is None
    assert "mosaic VMEM overflow" in entry["errors"]["pallas_b256"]
    assert "pallas_b256" not in entry["times"]


def test_forced_pallas_uses_cached_block(tune_cache, monkeypatch):
    """mode=pallas consults the tune cache for the block but never
    benchmarks; off-TPU it runs in interpret mode."""
    import jax

    backend = jax.default_backend()
    tune_cache[(6, 1, 64, backend)] = {"choice": "pallas", "block": 256,
                                       "times": {}, "errors": {}}
    monkeypatch.setenv("RAFT_TPU_SMALLSOLVE", "pallas")
    kind, block, interpret = smallsolve._solver_choice(6, 1, 64)
    assert kind == "pallas" and block == 256
    assert interpret == (backend != "tpu")
    assert smallsolve.use_pallas(6, 1, 64) is True
    assert smallsolve.use_pallas() is True


@pytest.mark.slow
def test_autotune_real_timing_records_entry(tune_cache):
    """Real (unmocked) autotune on a small problem: runs both paths on
    this backend, records times, and picks SOME winner."""
    entry = smallsolve.autotune(4, 1, 130, candidates=[128])
    assert entry["choice"] in ("jnp", "pallas")
    assert entry["times"]["jnp"] > 0.0
    assert set(entry["times"]) >= {"jnp"}
    # the decision is what the dispatcher will serve for this size
    import jax

    key = (4, 1, 130, jax.default_backend())
    assert tune_cache[key] is entry
